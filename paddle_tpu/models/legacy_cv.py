"""Classic PaddleCV image_classification zoo tail: AlexNet, GoogLeNet
(Inception v1), ShuffleNetV2 — NHWC/TPU-native builds of the remaining
reference classification families (reference models live in the
PaddleCV models/image_classification zoo built on fluid layers/nn.py
conv2d/pool2d/fc; here they compose the same nn.layers primitives).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.nn.layers import Conv2D, Linear, Pool2D
from paddle_tpu.nn.module import Layer, LayerList
from paddle_tpu.models.common import classification_loss
from paddle_tpu.models.resnet import ConvBNLayer
from paddle_tpu.ops import nn as F


class AlexNet(Layer):
    """AlexNet (5 conv + 3 fc, LRN replaced by the modern BN idiom is
    NOT applied — the classic net uses plain conv+relu like the
    reference's AlexNet)."""

    def __init__(self, num_classes=1000, in_ch=3):
        super().__init__()
        self.conv1 = Conv2D(in_ch, 64, 11, stride=4, padding=2)
        self.conv2 = Conv2D(64, 192, 5, padding=2)
        self.conv3 = Conv2D(192, 384, 3, padding=1)
        self.conv4 = Conv2D(384, 256, 3, padding=1)
        self.conv5 = Conv2D(256, 256, 3, padding=1)
        self.pool = Pool2D(3, stride=2, pool_type="max")
        self.fc1 = Linear(256 * 6 * 6, 4096, sharding=None)
        self.fc2 = Linear(4096, 4096, sharding=None)
        self.fc3 = Linear(4096, num_classes, sharding=None)

    def forward(self, params, x, *, training=False, key=None):
        for name in ("conv1", "conv2"):
            x = jax.nn.relu(getattr(self, name)(params[name], x))
            x = self.pool(None, x)
        for name in ("conv3", "conv4", "conv5"):
            x = jax.nn.relu(getattr(self, name)(params[name], x))
        x = self.pool(None, x)
        # adaptive 6x6 like the canonical head (no-op for 224 inputs;
        # bilinear resample covers non-divisible test shapes)
        if x.shape[1:3] != (6, 6):
            if x.shape[1] % 6 == 0 and x.shape[2] % 6 == 0:
                x = F.adaptive_pool2d(x, 6, pool_type="avg")
            else:
                x = jax.image.resize(
                    x, (x.shape[0], 6, 6, x.shape[3]), "linear")
        x = x.reshape(x.shape[0], -1)
        ks = ([None, None] if key is None
              else list(jax.random.split(key, 2)))
        x = jax.nn.relu(self.fc1(params["fc1"], x))
        x = F.dropout(x, ks[0], rate=0.5,
                      training=training and ks[0] is not None)
        x = jax.nn.relu(self.fc2(params["fc2"], x))
        x = F.dropout(x, ks[1], rate=0.5,
                      training=training and ks[1] is not None)
        return self.fc3(params["fc3"], x)

    def loss(self, params, image, label, *, training=True, key=None):
        return classification_loss(
            self.forward(params, image, training=training, key=key),
            label)


class _Inception(Layer):
    """GoogLeNet inception block: 1x1 | 1x1-3x3 | 1x1-5x5 | pool-1x1."""

    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, cp):
        super().__init__()
        self.b1 = ConvBNLayer(in_ch, c1, 1, act="relu")
        self.b3r = ConvBNLayer(in_ch, c3r, 1, act="relu")
        self.b3 = ConvBNLayer(c3r, c3, 3, act="relu")
        self.b5r = ConvBNLayer(in_ch, c5r, 1, act="relu")
        self.b5 = ConvBNLayer(c5r, c5, 5, act="relu")
        self.bp = ConvBNLayer(in_ch, cp, 1, act="relu")
        self.pool = Pool2D(3, stride=1, padding=1, pool_type="max")
        self.out_ch = c1 + c3 + c5 + cp

    def forward(self, params, x, training=False):
        y1 = self.b1(params["b1"], x, training=training)
        y3 = self.b3(params["b3"],
                     self.b3r(params["b3r"], x, training=training),
                     training=training)
        y5 = self.b5(params["b5"],
                     self.b5r(params["b5r"], x, training=training),
                     training=training)
        yp = self.bp(params["bp"], self.pool(None, x),
                     training=training)
        return jnp.concatenate([y1, y3, y5, yp], axis=-1)


class GoogLeNet(Layer):
    """GoogLeNet / Inception v1 (PaddleCV GoogLeNet; aux heads omitted —
    the reference disables them at inference and modern training drops
    them)."""

    CFG = [  # (c1, c3r, c3, c5r, c5, cp)
        (64, 96, 128, 16, 32, 32),      # 3a
        (128, 128, 192, 32, 96, 64),    # 3b
        (192, 96, 208, 16, 48, 64),     # 4a
        (160, 112, 224, 24, 64, 64),    # 4b
        (128, 128, 256, 24, 64, 64),    # 4c
        (112, 144, 288, 32, 64, 64),    # 4d
        (256, 160, 320, 32, 128, 128),  # 4e
        (256, 160, 320, 32, 128, 128),  # 5a
        (384, 192, 384, 48, 128, 128),  # 5b
    ]
    POOL_AFTER = {1, 6}                 # maxpool after 3b and 4e

    def __init__(self, num_classes=1000, in_ch=3):
        super().__init__()
        self.stem1 = ConvBNLayer(in_ch, 64, 7, stride=2, act="relu")
        self.stem2 = ConvBNLayer(64, 64, 1, act="relu")
        self.stem3 = ConvBNLayer(64, 192, 3, act="relu")
        self.pool = Pool2D(3, stride=2, padding=1, pool_type="max")
        blocks = []
        ch = 192
        for cfg in self.CFG:
            blk = _Inception(ch, *cfg)
            blocks.append(blk)
            ch = blk.out_ch
        self.blocks = LayerList(blocks)
        self.fc = Linear(ch, num_classes, sharding=None)

    def forward(self, params, x, *, training=False, key=None):
        x = self.stem1(params["stem1"], x, training=training)
        x = self.pool(None, x)
        x = self.stem2(params["stem2"], x, training=training)
        x = self.stem3(params["stem3"], x, training=training)
        x = self.pool(None, x)
        for i, blk in enumerate(self.blocks):
            x = blk(params["blocks"][str(i)], x, training=training)
            if i in self.POOL_AFTER:
                x = self.pool(None, x)
        x = jnp.mean(x, axis=(1, 2))
        x = F.dropout(x, key, rate=0.4,
                      training=training and key is not None)
        return self.fc(params["fc"], x)

    def loss(self, params, image, label, *, training=True, key=None):
        return classification_loss(
            self.forward(params, image, training=training, key=key),
            label)


def channel_shuffle(x, groups):
    """(B, H, W, C) channel shuffle (shuffle_channel_op): interleave
    group channels."""
    b, h, w, c = x.shape
    x = x.reshape(b, h, w, groups, c // groups)
    return x.transpose(0, 1, 2, 4, 3).reshape(b, h, w, c)


class _ShuffleUnit(Layer):
    """ShuffleNetV2 unit: split-transform-concat-shuffle (stride 1) or
    dual-branch downsample (stride 2)."""

    def __init__(self, in_ch, out_ch, stride):
        super().__init__()
        self.stride = stride
        half = out_ch // 2
        branch_in = in_ch if stride == 2 else in_ch // 2
        self.r1 = ConvBNLayer(branch_in, half, 1, act="relu")
        self.rd = ConvBNLayer(half, half, 3, stride=stride,
                              groups=half)            # depthwise
        self.r2 = ConvBNLayer(half, half, 1, act="relu")
        if stride == 2:
            self.ld = ConvBNLayer(branch_in, branch_in, 3, stride=2,
                                  groups=branch_in)
            self.l1 = ConvBNLayer(branch_in, half, 1, act="relu")

    def forward(self, params, x, training=False):
        if self.stride == 1:
            left, right = jnp.split(x, 2, axis=-1)
        else:
            left = right = x
            left = self.l1(params["l1"],
                           self.ld(params["ld"], left,
                                   training=training),
                           training=training)
        right = self.r1(params["r1"], right, training=training)
        right = self.rd(params["rd"], right, training=training)
        right = self.r2(params["r2"], right, training=training)
        return channel_shuffle(
            jnp.concatenate([left, right], axis=-1), 2)


class ShuffleNetV2(Layer):
    """ShuffleNetV2 1.0x (PaddleCV ShuffleNetV2; stage channels for the
    1.0x width)."""

    STAGES = [(4, 116), (8, 232), (4, 464)]

    def __init__(self, num_classes=1000, in_ch=3):
        super().__init__()
        self.stem = ConvBNLayer(in_ch, 24, 3, stride=2, act="relu")
        self.pool = Pool2D(3, stride=2, padding=1, pool_type="max")
        units = []
        ch = 24
        for reps, out in self.STAGES:
            units.append(_ShuffleUnit(ch, out, stride=2))
            for _ in range(reps - 1):
                units.append(_ShuffleUnit(out, out, stride=1))
            ch = out
        self.units = LayerList(units)
        self.tail = ConvBNLayer(ch, 1024, 1, act="relu")
        self.fc = Linear(1024, num_classes, sharding=None)

    def forward(self, params, x, *, training=False, key=None):
        x = self.stem(params["stem"], x, training=training)
        x = self.pool(None, x)
        for i, u in enumerate(self.units):
            x = u(params["units"][str(i)], x, training=training)
        x = self.tail(params["tail"], x, training=training)
        x = jnp.mean(x, axis=(1, 2))
        return self.fc(params["fc"], x)

    def loss(self, params, image, label, *, training=True, key=None):
        return classification_loss(
            self.forward(params, image, training=training, key=key),
            label)


class _DarkResidual(Layer):
    """DarkNet53 residual: 1x1 squeeze + 3x3 expand, additive skip."""

    def __init__(self, ch):
        super().__init__()
        self.c1 = ConvBNLayer(ch, ch // 2, 1, act="leaky")
        self.c2 = ConvBNLayer(ch // 2, ch, 3, act="leaky")

    def forward(self, params, x, training=False):
        h = self.c1(params["c1"], x, training=training)
        h = self.c2(params["c2"], h, training=training)
        return x + h


class DarkNet53(Layer):
    """DarkNet53 (the reference YOLOv3 backbone — PaddleCV/PaddleDetection
    darknet.py): conv-bn-leaky trunk with (1, 2, 8, 8, 4) residual
    stages. Exposes the same ``features(...endpoints=)`` /
    ``block_channels`` contract as MobileNetV1 so detectors swap
    backbones freely. Stride-8/16/32 endpoints sit at block indices
    13 / 22 / 27 (= -1)."""

    STAGE_REPS = (1, 2, 8, 8, 4)

    def __init__(self, num_classes=1000, in_ch=3, scale=1.0):
        super().__init__()

        def c(n):
            return max(8, int(n * scale))

        self.stem = ConvBNLayer(in_ch, c(32), 3, act="leaky")
        blocks, widths = [], []
        ch = c(32)
        for i, reps in enumerate(self.STAGE_REPS):
            out = c(64 * (2 ** i))
            blocks.append(ConvBNLayer(ch, out, 3, stride=2, act="leaky"))
            widths.append(out)
            for _ in range(reps):
                blocks.append(_DarkResidual(out))
                widths.append(out)
            ch = out
        self.blocks = LayerList(blocks)
        self.block_channels = widths
        self.fc = Linear(ch, num_classes, sharding=None)

    def features(self, params, x, training=False, *, endpoints=()):
        """Forward through the trunk; returns (final, {idx: feat})."""
        x = self.stem(params["stem"], x, training=training)
        feats = {}
        for i, block in enumerate(self.blocks):
            x = block(params["blocks"][str(i)], x, training=training)
            if i in endpoints:
                feats[i] = x
        return x, feats

    def forward(self, params, x, *, training=False, key=None):
        x, _ = self.features(params, x, training=training)
        x = jnp.mean(x, axis=(1, 2))
        return self.fc(params["fc"], x)

    def loss(self, params, image, label, *, training=True, key=None):
        return classification_loss(
            self.forward(params, image, training=training), label)


class _Fire(Layer):
    """SqueezeNet fire module: 1x1 squeeze -> parallel 1x1 + 3x3 expand."""

    def __init__(self, in_ch, s1, e1, e3):
        super().__init__()
        self.squeeze = Conv2D(in_ch, s1, 1)
        self.e1 = Conv2D(s1, e1, 1)
        self.e3 = Conv2D(s1, e3, 3, padding=1)
        self.out_ch = e1 + e3

    def forward(self, params, x, training=False):
        s = jax.nn.relu(self.squeeze(params["squeeze"], x))
        return jnp.concatenate(
            [jax.nn.relu(self.e1(params["e1"], s)),
             jax.nn.relu(self.e3(params["e3"], s))], axis=-1)


class SqueezeNet(Layer):
    """SqueezeNet 1.1 (PaddleCV SqueezeNet): conv stem + 8 fire modules
    + per-class 1x1 conv head with global average pooling."""

    CFG = [(16, 64, 64), (16, 64, 64), (32, 128, 128), (32, 128, 128),
           (48, 192, 192), (48, 192, 192), (64, 256, 256),
           (64, 256, 256)]
    POOL_AFTER = {1, 3}      # maxpool after fire3/fire5 (1.1 layout;
    #   list indices 1 and 3 — fires are named from fire2 in the paper)

    def __init__(self, num_classes=1000, in_ch=3):
        super().__init__()
        self.stem = Conv2D(in_ch, 64, 3, stride=2, padding=1)
        self.pool = Pool2D(3, stride=2, padding=1, pool_type="max")
        fires = []
        ch = 64
        for cfg in self.CFG:
            f = _Fire(ch, *cfg)
            fires.append(f)
            ch = f.out_ch
        self.fires = LayerList(fires)
        self.head = Conv2D(ch, num_classes, 1)

    def forward(self, params, x, *, training=False, key=None):
        x = jax.nn.relu(self.stem(params["stem"], x))
        x = self.pool(None, x)
        for i, f in enumerate(self.fires):
            x = f(params["fires"][str(i)], x, training=training)
            if i in self.POOL_AFTER:
                x = self.pool(None, x)
        x = F.dropout(x, key, rate=0.5,
                      training=training and key is not None)
        x = jax.nn.relu(self.head(params["head"], x))
        return jnp.mean(x, axis=(1, 2))          # (B, num_classes)

    def loss(self, params, image, label, *, training=True, key=None):
        return classification_loss(
            self.forward(params, image, training=training, key=key),
            label)


class _DenseBlock(Layer):
    """DenseNet block: L bottleneck (1x1 then 3x3 conv-bn-relu) layers,
    each consuming the concat of all previous features. NOTE: uses this
    codebase's post-activation ConvBNLayer idiom, not the paper's
    pre-activation BN-ReLU-conv ordering — same connectivity, different
    tensor layout for checkpoint porting."""

    def __init__(self, in_ch, growth, reps):
        super().__init__()
        layers = []
        ch = in_ch
        for _ in range(reps):
            layers.append(LayerList([
                ConvBNLayer(ch, 4 * growth, 1, act="relu"),
                ConvBNLayer(4 * growth, growth, 3, act="relu")]))
            ch += growth
        self.layers = LayerList(layers)
        self.out_ch = ch

    def forward(self, params, x, training=False):
        for i, pair in enumerate(self.layers):
            p = params["layers"][str(i)]
            h = pair[0](p["0"], x, training=training)
            h = pair[1](p["1"], h, training=training)
            x = jnp.concatenate([x, h], axis=-1)
        return x


class DenseNet121(Layer):
    """DenseNet-121 (PaddleCV DenseNet; growth 32, blocks 6/12/24/16,
    0.5x transition compression)."""

    BLOCKS = (6, 12, 24, 16)

    def __init__(self, num_classes=1000, in_ch=3, growth=32):
        super().__init__()
        self.stem = ConvBNLayer(in_ch, 2 * growth, 7, stride=2,
                                act="relu")
        self.pool = Pool2D(3, stride=2, padding=1, pool_type="max")
        self.avg = Pool2D(2, stride=2, pool_type="avg")
        blocks, trans = [], []
        ch = 2 * growth
        for i, reps in enumerate(self.BLOCKS):
            blk = _DenseBlock(ch, growth, reps)
            blocks.append(blk)
            ch = blk.out_ch
            if i < len(self.BLOCKS) - 1:
                trans.append(ConvBNLayer(ch, ch // 2, 1, act="relu"))
                ch //= 2
        self.blocks = LayerList(blocks)
        self.trans = LayerList(trans)
        self.fc = Linear(ch, num_classes, sharding=None)

    def forward(self, params, x, *, training=False, key=None):
        x = self.stem(params["stem"], x, training=training)
        x = self.pool(None, x)
        for i, blk in enumerate(self.blocks):
            x = blk(params["blocks"][str(i)], x, training=training)
            if i < len(self.trans):
                x = self.trans[i](params["trans"][str(i)], x,
                                  training=training)
                x = self.avg(None, x)
        x = jnp.mean(x, axis=(1, 2))
        return self.fc(params["fc"], x)

    def loss(self, params, image, label, *, training=True, key=None):
        return classification_loss(
            self.forward(params, image, training=training), label)
