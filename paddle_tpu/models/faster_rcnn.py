"""Faster R-CNN (two-stage detector) — PaddleCV rcnn model family parity,
composed end-to-end from the TPU-native detection op stack:
anchor_generator -> rpn_target_assign -> generate_proposals ->
generate_proposal_labels -> roi_align -> box head, all static-shape
(validity masks carry the dynamic counts; the reference threads LoD
tensors through the same pipeline —
python/paddle/fluid/tests/unittests/test_generate_proposals_op.py,
layers/detection.py rpn_target_assign/generate_proposals).

TPU design notes: every stage is fixed-shape so ONE compiled program
serves every image; proposal sampling uses the deterministic rank-capped
subsample (pass ``key`` for the reference's randomized variant); the RoI
head runs on exactly ``roi_batch`` sampled proposals per image.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.models.mobilenet import MobileNetV1
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Conv2D, Linear
from paddle_tpu.nn.module import Layer
from paddle_tpu.ops import detection as D
from paddle_tpu.ops import nn as ops_nn


@dataclasses.dataclass
class FasterRCNNConfig:
    num_classes: int = 21                 # incl. background = 0
    image_size: int = 224
    backbone_scale: float = 1.0
    anchor_sizes: Tuple[int, ...] = (32, 64, 128)
    aspect_ratios: Tuple[float, ...] = (0.5, 1.0, 2.0)
    pre_nms_top_n: int = 256
    post_nms_top_n: int = 64              # proposals kept per image
    roi_batch: int = 32                   # sampled rois for the head
    fg_fraction: float = 0.25
    roi_size: int = 7
    head_dim: int = 256
    rpn_batch: int = 64

    @classmethod
    def tiny(cls, num_classes=4, image_size=64):
        return cls(num_classes=num_classes, image_size=image_size,
                   backbone_scale=0.125, anchor_sizes=(16, 32),
                   aspect_ratios=(1.0,), pre_nms_top_n=32,
                   post_nms_top_n=16, roi_batch=16, head_dim=32,
                   rpn_batch=16)


class FasterRCNN(Layer):
    """Backbone (stride 16) -> RPN -> proposals -> RoIAlign -> box head."""

    def __init__(self, cfg: FasterRCNNConfig):
        super().__init__()
        self.cfg = cfg
        self.backbone = MobileNetV1(num_classes=1,
                                    scale=cfg.backbone_scale)
        self._endpoint = 10               # stride-16 feature map
        feat_ch = self.backbone.block_channels[self._endpoint]
        a = len(cfg.anchor_sizes) * len(cfg.aspect_ratios)
        self.num_anchors = a
        self.rpn_conv = Conv2D(feat_ch, cfg.head_dim, 3, padding=1)
        self.rpn_cls = Conv2D(cfg.head_dim, a, 1)
        self.rpn_reg = Conv2D(cfg.head_dim, 4 * a, 1)
        in_head = feat_ch * cfg.roi_size * cfg.roi_size
        self.fc1 = Linear(in_head, cfg.head_dim, sharding=None)
        self.fc2 = Linear(cfg.head_dim, cfg.head_dim, sharding=None)
        self.cls_head = Linear(cfg.head_dim, cfg.num_classes,
                               weight_init=I.normal(std=0.01), sharding=None)
        self.reg_head = Linear(cfg.head_dim, 4 * cfg.num_classes,
                               weight_init=I.normal(std=0.001), sharding=None)

    # ---- stages ----------------------------------------------------------

    def _features(self, params, image, training):
        _, feats = self.backbone.features(
            params["backbone"], image, training=training,
            endpoints=(self._endpoint,))
        return feats[self._endpoint]

    def _rpn(self, params, feat):
        h = jax.nn.relu(self.rpn_conv(params["rpn_conv"], feat))
        scores = self.rpn_cls(params["rpn_cls"], h)      # (B, H, W, A)
        deltas = self.rpn_reg(params["rpn_reg"], h)      # (B, H, W, 4A)
        b, fh, fw, _ = scores.shape
        stride = self.cfg.image_size // fh
        anchors, _ = D.anchor_generator(
            fh, fw, anchor_sizes=self.cfg.anchor_sizes,
            aspect_ratios=self.cfg.aspect_ratios,
            stride=(float(stride), float(stride)))
        return (scores.reshape(b, -1), deltas.reshape(b, -1, 4), anchors)

    def _pool(self, feat_i, rois):
        return D.roi_align(
            feat_i, rois,
            output_size=(self.cfg.roi_size, self.cfg.roi_size),
            spatial_scale=feat_i.shape[0] / self.cfg.image_size)

    def _head_pooled(self, params, pooled):
        flat = pooled.reshape(pooled.shape[0], -1)
        h = jax.nn.relu(self.fc1(params["fc1"], flat))
        h = jax.nn.relu(self.fc2(params["fc2"], h))
        return (self.cls_head(params["cls_head"], h),
                self.reg_head(params["reg_head"], h))

    def _head(self, params, feat_i, rois):
        return self._head_pooled(params, self._pool(feat_i, rois))

    # ---- training --------------------------------------------------------

    def _stage_losses(self, params, feat_i, score_i, delta_i, anchors,
                      im_shape, gt_b, gt_l, gt_m):
        """Per-image RPN + RoI-head losses. Also returns the sampled-RoI
        auxiliaries (rois/labels/fg/matched-gt) so subclasses — the mask
        branch — can supervise additional heads on the same sample."""
        cfg = self.cfg
        # --- RPN losses
        labels, tgt, fg, bg = D.rpn_target_assign(
            anchors, gt_b, gt_m, im_shape=im_shape,
            batch_size_per_im=cfg.rpn_batch)
        obj = ops_nn.sigmoid_cross_entropy_with_logits(
            score_i, (labels == 1).astype(score_i.dtype))
        used = labels >= 0
        rpn_cls_l = (obj * used).sum() / jnp.maximum(used.sum(), 1)
        rpn_reg_l = (ops_nn.smooth_l1(
            delta_i, jax.lax.stop_gradient(tgt)).sum(-1)
            * fg).sum() / jnp.maximum(fg.sum(), 1)

        # --- proposals (gradients stop at sampled boxes)
        rois, _, valid = D.generate_proposals(
            jax.lax.stop_gradient(score_i),
            jax.lax.stop_gradient(delta_i), anchors, im_shape,
            pre_nms_top_n=cfg.pre_nms_top_n,
            post_nms_top_n=cfg.post_nms_top_n, min_size=4.0)
        rois = jax.lax.stop_gradient(rois)
        # mix in gt boxes as guaranteed-quality proposals (reference
        # generate_proposal_labels does the same)
        rois = jnp.concatenate([rois, gt_b])
        valid = jnp.concatenate([valid, gt_m])
        roi_labels, roi_tgt, roi_fg, roi_bg, roi_match = \
            D.generate_proposal_labels(
                rois, valid, gt_b, gt_l, gt_m,
                batch_size_per_im=cfg.roi_batch,
                fg_fraction=cfg.fg_fraction, return_matches=True)

        # --- RoI head on a FIXED roi_batch subset
        sampled = roi_fg | roi_bg
        order = jnp.argsort(~sampled)         # sampled first, stable
        pick = order[:cfg.roi_batch]
        rois_s = rois[pick]
        lab_s = roi_labels[pick]
        tgt_s = roi_tgt[pick]
        use_s = sampled[pick]
        pooled = self._pool(feat_i, rois_s)   # shared with the mask head
        cls_logits, reg = self._head_pooled(params, pooled)
        logp = jax.nn.log_softmax(cls_logits.astype(jnp.float32), -1)
        ce = -jnp.take_along_axis(
            logp, jnp.maximum(lab_s, 0)[:, None], -1)[:, 0]
        head_cls_l = (ce * use_s).sum() / jnp.maximum(use_s.sum(), 1)
        reg = reg.reshape(cfg.roi_batch, cfg.num_classes, 4)
        reg_sel = jnp.take_along_axis(
            reg, jnp.maximum(lab_s, 0)[:, None, None].repeat(4, -1),
            1)[:, 0]
        fg_s = use_s & (lab_s > 0)
        head_reg_l = (ops_nn.smooth_l1(
            reg_sel, jax.lax.stop_gradient(tgt_s)).sum(-1)
            * fg_s).sum() / jnp.maximum(fg_s.sum(), 1)
        total = rpn_cls_l + rpn_reg_l + head_cls_l + head_reg_l
        aux = dict(rois=rois_s, labels=lab_s, use=use_s, fg=fg_s,
                   match=roi_match[pick], pooled=pooled)
        return total, aux

    def loss(self, params, image, gt_boxes, gt_labels, gt_mask, *,
             training=True, key=None):
        """gt_boxes (B, G, 4) PIXEL xyxy; gt_labels (B, G) in [1, C)."""
        cfg = self.cfg
        feat = self._features(params, image, training)
        scores, deltas, anchors = self._rpn(params, feat)
        im_shape = jnp.asarray([cfg.image_size, cfg.image_size],
                               jnp.float32)

        def one(feat_i, score_i, delta_i, gt_b, gt_l, gt_m):
            total, _ = self._stage_losses(
                params, feat_i, score_i, delta_i, anchors, im_shape,
                gt_b, gt_l, gt_m)
            return total

        losses = jax.vmap(one)(feat, scores, deltas, gt_boxes, gt_labels,
                               gt_mask)
        return losses.mean(), {}

    # ---- inference -------------------------------------------------------

    def detect(self, params, image, *, score_threshold=0.05,
               nms_threshold=0.5, max_per_class=10, feat=None):
        """``feat``: pass precomputed backbone features to share them
        with other heads (MaskRCNN.segment computes them once)."""
        cfg = self.cfg
        if feat is None:
            feat = self._features(params, image, training=False)
        scores, deltas, anchors = self._rpn(params, feat)
        im_shape = jnp.asarray([cfg.image_size, cfg.image_size],
                               jnp.float32)

        def one(feat_i, score_i, delta_i):
            rois, _, valid = D.generate_proposals(
                score_i, delta_i, anchors, im_shape,
                pre_nms_top_n=cfg.pre_nms_top_n,
                post_nms_top_n=cfg.post_nms_top_n, min_size=4.0)
            cls_logits, reg = self._head(params, feat_i, rois)
            probs = jax.nn.softmax(cls_logits.astype(jnp.float32), -1)
            probs = probs * valid[:, None]
            reg = reg.reshape(rois.shape[0], cfg.num_classes, 4)
            # decode per-class boxes; class 0 = background dropped.
            # Per-class NMS (multiclass_nms) — one flat NMS would let
            # overlapping objects of DIFFERENT classes suppress each other
            boxes_c = jax.vmap(
                lambda dc: D.box_clip(D.box_decode(dc, rois), im_shape),
                in_axes=1, out_axes=1)(reg)       # (R, C, 4)
            # multiclass_nms shares one box set across classes: use the
            # per-roi best-foreground-class decoded box as that set
            best_c = jnp.argmax(probs[:, 1:], axis=-1) + 1
            cand = jnp.take_along_axis(
                boxes_c, best_c[:, None, None].repeat(4, -1), 1)[:, 0]
            cls_ids, idxs, ok = D.multiclass_nms(
                cand, probs[:, 1:], iou_threshold=nms_threshold,
                score_threshold=score_threshold,
                max_per_class=max_per_class)
            sel = jnp.where(ok, probs[idxs, cls_ids + 1], 0.0)
            return cand[idxs], cls_ids + 1, sel, ok

        return jax.vmap(one)(feat, scores, deltas)


class MaskRCNN(FasterRCNN):
    """Mask R-CNN: Faster R-CNN + a per-class mask branch
    (PaddleCV rcnn MaskRCNN parity — reference builds the mask head as
    RoI pool -> convs -> deconv -> 1x1 over the sampled foregrounds with
    targets from generate_mask_labels_op; here the branch rides the same
    sampled RoI batch ``_stage_losses`` exposes).

    Mask resolution = 2 * roi_size (RoIAlign at roi_size, one stride-2
    deconv doubles it), matching the reference's 14 -> 28 shape at the
    standard roi_size."""

    def __init__(self, cfg: FasterRCNNConfig):
        super().__init__(cfg)
        feat_ch = self.backbone.block_channels[self._endpoint]
        d = cfg.head_dim
        self.mask_conv = Conv2D(feat_ch, d, 3, padding=1)
        self.mask_deconv = self.create_parameter(
            "mask_deconv", (2, 2, d, d),
            initializer=I.msra_normal(fan_in=d * 4))
        self.mask_pred = Conv2D(d, cfg.num_classes, 1,
                                weight_init=I.normal(std=0.01))
        self.mask_resolution = 2 * cfg.roi_size

    def _mask_head(self, params, feat_i, rois, pooled=None):
        """(R, 4) rois -> per-class mask logits (R, 2s, 2s, C).
        ``pooled``: reuse already-RoIAligned features (training shares
        _stage_losses' pooling; RoIAlign is the gather-heavy op)."""
        if pooled is None:
            pooled = self._pool(feat_i, rois)
        h = jax.nn.relu(self.mask_conv(params["mask_conv"], pooled))
        h = ops_nn.conv2d_transpose(
            h, params["mask_deconv"].astype(h.dtype), stride=2)
        h = jax.nn.relu(h)
        return self.mask_pred(params["mask_pred"], h)

    def loss(self, params, image, gt_boxes, gt_labels, gt_mask,
             gt_inst_masks, *, training=True, key=None):
        """As FasterRCNN.loss plus ``gt_inst_masks`` (B, G, Hm, Hm)
        binary instance rasters at image scale (square — see
        generate_mask_labels)."""
        cfg = self.cfg
        feat = self._features(params, image, training)
        scores, deltas, anchors = self._rpn(params, feat)
        im_shape = jnp.asarray([cfg.image_size, cfg.image_size],
                               jnp.float32)

        def one(feat_i, score_i, delta_i, gt_b, gt_l, gt_m, gt_im):
            det_l, aux = self._stage_losses(
                params, feat_i, score_i, delta_i, anchors, im_shape,
                gt_b, gt_l, gt_m)
            targets, w = D.generate_mask_labels(
                aux["rois"], aux["match"], aux["fg"], gt_im,
                resolution=self.mask_resolution, im_size=cfg.image_size)
            logits = self._mask_head(params, feat_i, aux["rois"],
                                     pooled=aux["pooled"])
            cls = jnp.maximum(aux["labels"], 0)
            sel = jnp.take_along_axis(
                logits, cls[:, None, None, None], axis=-1)[..., 0]
            bce = ops_nn.sigmoid_cross_entropy_with_logits(
                sel, jax.lax.stop_gradient(targets)).mean(axis=(1, 2))
            mask_l = (bce * w).sum() / jnp.maximum(w.sum(), 1.0)
            return det_l + mask_l, mask_l

        losses, mask_ls = jax.vmap(one)(
            feat, scores, deltas, gt_boxes, gt_labels, gt_mask,
            gt_inst_masks)
        return losses.mean(), {"mask_loss": mask_ls.mean()}

    def segment(self, params, image, *, score_threshold=0.05,
                nms_threshold=0.5, max_per_class=10,
                binarize_threshold=0.5):
        """detect() plus a sigmoid instance mask per kept detection:
        returns (boxes, classes, scores, valid, masks (B, K, 2s, 2s))."""
        feat = self._features(params, image, training=False)
        boxes, classes, det_scores, ok = self.detect(
            params, image, score_threshold=score_threshold,
            nms_threshold=nms_threshold, max_per_class=max_per_class,
            feat=feat)

        def one(feat_i, boxes_i, cls_i):
            logits = self._mask_head(params, feat_i, boxes_i)
            sel = jnp.take_along_axis(
                logits, cls_i[:, None, None, None], axis=-1)[..., 0]
            return jax.nn.sigmoid(sel)

        probs = jax.vmap(one)(feat, boxes, classes)
        masks = (probs >= binarize_threshold).astype(jnp.float32)
        masks = masks * ok[:, :, None, None]
        return boxes, classes, det_scores, ok, masks
