"""DCGAN — book/09.image_generation parity (test_image_generation* /
fluid GAN examples): transposed-conv generator + conv discriminator with
alternating adversarial updates. TPU-native: both networks are pytree
models; ``gan_step`` runs one D step + one G step as two jitted fused
updates (the reference alternates two programs over shared scopes)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import BatchNorm, Conv2D, Linear
from paddle_tpu.nn.module import Layer, LayerList
from paddle_tpu.ops import nn as ops_nn


class DCGANGenerator(Layer):
    """z (B, zdim) -> (B, s, s, out_ch) in [-1, 1]; s = 4 * 2^n_up."""

    def __init__(self, zdim=64, base=32, n_up=3, out_ch=1):
        super().__init__()
        self.base0 = base * (2 ** (n_up - 1))
        self.fc = Linear(zdim, 4 * 4 * self.base0, sharding=None)
        bns = []
        ch = self.base0
        for i in range(n_up):
            out = out_ch if i == n_up - 1 else ch // 2
            self.create_parameter(f"up{i}", (4, 4, ch, out),
                                  initializer=I.normal(std=0.02))
            if i != n_up - 1:
                bns.append(BatchNorm(out))
            ch = out
        self._n_up = n_up
        self.bns = LayerList(bns)

    def forward(self, params, z, training=False):
        x = self.fc(params["fc"], z).reshape(-1, 4, 4, self.base0)
        x = jax.nn.relu(x)
        for i in range(self._n_up):
            w = params[f"up{i}"]
            x = ops_nn.conv2d_transpose(x, w, stride=2, padding=1)
            if i != self._n_up - 1:
                x = self.bns[i](params["bns"][str(i)], x,
                                training=training)
                x = jax.nn.relu(x)
        return jnp.tanh(x)


class DCGANDiscriminator(Layer):
    def __init__(self, in_ch=1, base=32, n_down=3):
        super().__init__()
        convs, bns = [], []
        ch_in = in_ch
        ch = base
        for i in range(n_down):
            convs.append(Conv2D(ch_in, ch, 4, stride=2, padding=1,
                                weight_init=I.normal(std=0.02)))
            if i > 0:
                bns.append(BatchNorm(ch))
            ch_in = ch
            ch *= 2
        self.convs = LayerList(convs)
        self.bns = LayerList(bns)
        self.fc = Linear(ch_in * 4 * 4, 1, sharding=None)

    def forward(self, params, x, training=False):
        for i, conv in enumerate(self.convs):
            x = conv(params["convs"][str(i)], x)
            if i > 0:
                x = self.bns[i - 1](params["bns"][str(i - 1)], x,
                                    training=training)
            x = jax.nn.leaky_relu(x, 0.2)
        return self.fc(params["fc"], x.reshape(x.shape[0], -1))[:, 0]


def gan_step(gen, disc, g_opt, d_opt):
    """Returns jittable ``step(g_state, d_state, real, key) ->
    (g_state, d_state, metrics)`` doing one discriminator update (real
    vs fake, non-saturating BCE) then one generator update."""

    def d_loss(d_params, g_params, real, z):
        fake = gen(g_params, z, training=True)
        r = disc(d_params, real, training=True)
        f = disc(d_params, jax.lax.stop_gradient(fake), training=True)
        bce = ops_nn.sigmoid_cross_entropy_with_logits
        return (bce(r, jnp.ones_like(r)).mean()
                + bce(f, jnp.zeros_like(f)).mean())

    def g_loss(g_params, d_params, z):
        fake = gen(g_params, z, training=True)
        f = disc(d_params, fake, training=True)
        return ops_nn.sigmoid_cross_entropy_with_logits(
            f, jnp.ones_like(f)).mean()

    # note: BN running stats are not captured here (each forward uses
    # batch stats under training=True — the usual GAN practice); wrap
    # with nn.capture_state if inference-mode stats are needed

    def step(g_state, d_state, real, key):
        zdim = g_state["params"]["fc"]["weight"].shape[0]
        z1, z2 = jax.random.split(key)
        z = jax.random.normal(z1, (real.shape[0], zdim))
        dl, d_grads = jax.value_and_grad(d_loss)(
            d_state["params"], g_state["params"], real, z)
        d_new, d_opt_state = d_opt.update(d_grads, d_state["opt"],
                                          d_state["params"])
        d_state = dict(d_state, params=d_new, opt=d_opt_state)

        z = jax.random.normal(z2, (real.shape[0], zdim))
        gl, g_grads = jax.value_and_grad(g_loss)(
            g_state["params"], d_state["params"], z)
        g_new, g_opt_state = g_opt.update(g_grads, g_state["opt"],
                                          g_state["params"])
        g_state = dict(g_state, params=g_new, opt=g_opt_state)
        return g_state, d_state, {"d_loss": dl, "g_loss": gl}

    return step
