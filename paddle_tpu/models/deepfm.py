"""DeepFM CTR model — BASELINE.json config[4] (high-dim sparse embeddings).

Reference recipe: Paddle CTR models run on the async CPU/PS world — sparse
``lookup_table`` pulled from pservers/pslib (``DownpourWorker``,
``fleet_wrapper.h:76``), dense DNN towers trained hogwild. TPU-native: the
embedding table is GSPMD-sharded on-chip (parallel/embedding.py), the whole
model is one jitted step; FM + DNN towers are standard MXU matmuls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Linear
from paddle_tpu.nn.module import Layer, LayerList
from paddle_tpu.ops import nn as ops_nn
from paddle_tpu.parallel.embedding import ShardedEmbedding


class DeepFM(Layer):
    """inputs: feat_ids (B, F) int feature ids hashed into [0, vocab);
    optional feat_vals (B, F) float values (1.0 for categorical)."""

    def __init__(self, vocab_size, num_fields, embed_dim=8,
                 hidden=(400, 400, 400), axis="tp"):
        super().__init__()
        self.embedding = ShardedEmbedding(vocab_size, embed_dim, axis=axis)
        self.linear_w = ShardedEmbedding(vocab_size, 1, axis=axis)
        self.num_fields = num_fields
        layers = []
        in_dim = num_fields * embed_dim
        for h in hidden:
            layers.append(Linear(in_dim, h, sharding=None,
                                 weight_init=I.xavier_uniform()))
            in_dim = h
        self.dnn = LayerList(layers)
        self.dnn_out = Linear(in_dim, 1, sharding=None)
        self.bias = self.create_parameter("bias", (1,), initializer=I.zeros)

    def forward(self, params, feat_ids, feat_vals=None):
        b, f = feat_ids.shape
        if feat_vals is None:
            feat_vals = jnp.ones((b, f), jnp.float32)
        emb = self.embedding(params["embedding"], feat_ids)     # (B,F,D)
        emb = emb * feat_vals[..., None]
        # first order
        w = self.linear_w(params["linear_w"], feat_ids)[..., 0]  # (B,F)
        first = (w * feat_vals).sum(-1)
        # FM second order: 0.5 * ((sum e)^2 - sum e^2)
        s = emb.sum(axis=1)
        second = 0.5 * (s * s - (emb * emb).sum(axis=1)).sum(-1)
        # DNN tower
        h = emb.reshape(b, -1)
        for i, layer in enumerate(self.dnn):
            h = jax.nn.relu(layer(params["dnn"][str(i)], h))
        dnn_logit = self.dnn_out(params["dnn_out"], h)[:, 0]
        return first + second + dnn_logit + params["bias"][0]

    def loss(self, params, feat_ids, label, feat_vals=None):
        """label: (B,) float 0/1 click. Returns (logloss, {auc-ready probs})."""
        logits = self.forward(params, feat_ids, feat_vals)
        loss = ops_nn.sigmoid_cross_entropy_with_logits(
            logits, label.astype(jnp.float32)).mean()
        return loss, {"prob_mean": jax.nn.sigmoid(logits).mean()}
