"""DeepFM CTR model — BASELINE.json config[4] (high-dim sparse embeddings).

Reference recipe: Paddle CTR models run on the async CPU/PS world — sparse
``lookup_table`` pulled from pservers/pslib (``DownpourWorker``,
``fleet_wrapper.h:76``), dense DNN towers trained hogwild. TPU-native, two
placements for the table (parallel/host_kv.fits_hbm decides):

- :class:`DeepFM` — table fits HBM: GSPMD vocab-parallel sharding
  (parallel/embedding.py), whole model one jitted step.
- :class:`DeepFMHostKV` — beyond-HBM table: rows live in the host KV store
  (parallel/host_kv.py); the jitted step takes the batch's pulled rows as a
  differentiable input (grad w.r.t. rows = XLA scatter-add) and the host
  applies the sparse optimizer. pslib-style combined value layout: row =
  [w_linear, e_0..e_{D-1}] (one table, multiple value fields).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Linear
from paddle_tpu.nn.module import Layer, LayerList
from paddle_tpu.ops import nn as ops_nn


class _DeepFMTowers(Layer):
    """Shared dense half: FM second-order + DNN tower + bias over
    already-gathered embeddings."""

    def __init__(self, num_fields, embed_dim=8, hidden=(400, 400, 400)):
        super().__init__()
        self.num_fields = num_fields
        self.embed_dim = embed_dim
        layers = []
        in_dim = num_fields * embed_dim
        for h in hidden:
            layers.append(Linear(in_dim, h, sharding=None,
                                 weight_init=I.xavier_uniform()))
            in_dim = h
        self.dnn = LayerList(layers)
        self.dnn_out = Linear(in_dim, 1, sharding=None)
        self.bias = self.create_parameter("bias", (1,), initializer=I.zeros)

    def forward_embedded(self, params, emb, w, feat_vals=None):
        """emb: (B, F, D) per-feature embeddings; w: (B, F) first-order
        weights; returns (B,) logits."""
        b, f, _ = emb.shape
        if feat_vals is None:
            feat_vals = jnp.ones((b, f), jnp.float32)
        emb = emb * feat_vals[..., None]
        first = (w * feat_vals).sum(-1)
        # FM second order: 0.5 * ((sum e)^2 - sum e^2)
        s = emb.sum(axis=1)
        second = 0.5 * (s * s - (emb * emb).sum(axis=1)).sum(-1)
        h = emb.reshape(b, -1)
        for i, layer in enumerate(self.dnn):
            h = jax.nn.relu(layer(params["dnn"][str(i)], h))
        dnn_logit = self.dnn_out(params["dnn_out"], h)[:, 0]
        return first + second + dnn_logit + params["bias"][0]

    def _loss(self, logits, label):
        loss = ops_nn.sigmoid_cross_entropy_with_logits(
            logits, label.astype(jnp.float32)).mean()
        return loss, {"prob_mean": jax.nn.sigmoid(logits).mean()}


class DeepFM(_DeepFMTowers):
    """On-chip table variant. inputs: feat_ids (B, F) int feature ids
    hashed into [0, vocab); optional feat_vals (B, F) float values."""

    def __init__(self, vocab_size, num_fields, embed_dim=8,
                 hidden=(400, 400, 400), axis="tp"):
        super().__init__(num_fields, embed_dim, hidden)
        # local import: keep the towers importable without mesh machinery
        from paddle_tpu.parallel.embedding import ShardedEmbedding
        self.embedding = ShardedEmbedding(vocab_size, embed_dim, axis=axis)
        self.linear_w = ShardedEmbedding(vocab_size, 1, axis=axis)

    def forward(self, params, feat_ids, feat_vals=None):
        emb = self.embedding(params["embedding"], feat_ids)     # (B,F,D)
        w = self.linear_w(params["linear_w"], feat_ids)[..., 0]  # (B,F)
        return self.forward_embedded(params, emb, w, feat_vals)

    def loss(self, params, feat_ids, label, feat_vals=None):
        """label: (B,) float 0/1 click. Returns (logloss, aux)."""
        return self._loss(self.forward(params, feat_ids, feat_vals), label)


class DeepFMHostKV(_DeepFMTowers):
    """Beyond-HBM variant: device params are the towers only; the sparse
    table is a :class:`~paddle_tpu.parallel.host_kv.HostKVStore` with
    ``dim = 1 + embed_dim`` and the step consumes its pulled rows.

    row layout: ``rows[:, 0]`` first-order weight, ``rows[:, 1:]`` embedding.
    """

    kv_dim_for = staticmethod(lambda embed_dim: 1 + embed_dim)

    def forward(self, params, rows, inv, feat_vals=None):
        """rows: (U_pad, 1+D) pulled rows (differentiable input);
        inv: (B, F) int indices into rows."""
        gathered = jnp.take(rows, inv, axis=0)    # (B, F, 1+D)
        w = gathered[..., 0]
        emb = gathered[..., 1:]
        return self.forward_embedded(params, emb, w, feat_vals)

    def loss(self, params, rows, inv, label, feat_vals=None):
        return self._loss(self.forward(params, rows, inv, feat_vals), label)

    def predict_proba(self, params, rows, inv, feat_vals=None):
        """Serving forward: (B,) click probabilities from pulled rows.
        The embedding-serving engine jits this per row-bucket width —
        ``rows`` may carry trailing padding lanes (``inv`` never points
        at them), so one compiled shape serves any batch whose unique
        ids fit the bucket."""
        return jax.nn.sigmoid(self.forward(params, rows, inv, feat_vals))
