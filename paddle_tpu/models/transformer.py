"""Transformer enc-dec (base/big) for WMT en-de — BASELINE.json config[3].

Reference recipe: PaddleNLP transformer (fluid builds it from layers/nn.py
primitives + while_op beam search ``operators/*beam_search*``). TPU-native:
flash-attention encoder/decoder stacks (nn/transformer.py), packed static
shapes with padding masks instead of LoD ragged tensors (SURVEY.md §5.7),
label-smoothed xent, greedy/incremental decode via lax.while_loop.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Dropout, Embedding, LayerNorm
from paddle_tpu.nn.module import Layer, LayerList
from paddle_tpu.nn.transformer import (TransformerDecoderLayer,
                                       TransformerEncoderLayer)
from paddle_tpu.ops import attention as ops_attn


@dataclasses.dataclass
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    num_heads: int = 8
    ffn_size: int = 2048
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    max_len: int = 256
    dropout: float = 0.1
    attn_dropout: Optional[float] = None  # None = follow dropout; set 0
                                          # to enable attn_impl="ring"
    label_smoothing: float = 0.1
    bos_id: int = 0
    eos_id: int = 1
    pad_id: int = 2
    pre_ln: bool = True
    attn_impl: str = "auto"
    # pipeline parallelism over the "pp" mesh axis (parallel/pipeline.py):
    # encoder and decoder stacks each run as a pipelined stage sequence.
    # Applies to the dense (padded) loss/forward path; the packed-varlen
    # path (loss_packed) runs the stacks sequentially — see encode_packed.
    pipeline: bool = False
    pp_microbatches: int = 2
    pp_schedule: str = "gpipe"    # or "circular" (interleaved 1F1B)
    pp_circuits: int = 1

    @classmethod
    def big(cls, **kw):
        """Transformer-big (Vaswani et al. table 3)."""
        return cls(d_model=1024, num_heads=16, ffn_size=4096, **kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 64)
        kw.setdefault("d_model", 16)
        kw.setdefault("num_heads", 2)
        kw.setdefault("ffn_size", 32)
        kw.setdefault("num_encoder_layers", 2)
        kw.setdefault("num_decoder_layers", 2)
        kw.setdefault("max_len", 32)
        return cls(**kw)


def sinusoid_positions(max_len, dim):
    pos = jnp.arange(max_len, dtype=jnp.float32)[:, None]
    i = jnp.arange(dim // 2, dtype=jnp.float32)[None, :]
    angle = pos / jnp.power(10000.0, 2 * i / dim)
    return jnp.concatenate([jnp.sin(angle), jnp.cos(angle)],
                           axis=-1)  # (max_len, dim)


class Transformer(Layer):
    """Shared-vocab encoder-decoder with tied output projection."""

    def __init__(self, cfg: TransformerConfig):
        super().__init__()
        self.cfg = cfg
        self.embed = Embedding(cfg.vocab_size, cfg.d_model,
                               weight_init=I.normal(0.0, cfg.d_model ** -0.5))
        self.drop = Dropout(cfg.dropout)
        self.encoder = LayerList([
            TransformerEncoderLayer(cfg.d_model, cfg.num_heads, cfg.ffn_size,
                                    dropout=cfg.dropout,
                                    attn_dropout=cfg.attn_dropout,
                                    activation="relu", pre_ln=cfg.pre_ln,
                                    attn_impl=cfg.attn_impl)
            for _ in range(cfg.num_encoder_layers)])
        self.decoder = LayerList([
            TransformerDecoderLayer(cfg.d_model, cfg.num_heads, cfg.ffn_size,
                                    dropout=cfg.dropout,
                                    attn_dropout=cfg.attn_dropout,
                                    activation="relu", pre_ln=cfg.pre_ln,
                                    attn_impl=cfg.attn_impl)
            for _ in range(cfg.num_decoder_layers)])
        # pre-LN stacks need a final LayerNorm
        self.enc_ln = LayerNorm(cfg.d_model)
        self.dec_ln = LayerNorm(cfg.d_model)

    def _embed(self, params, ids, key=None, training=False):
        cfg = self.cfg
        x = self.embed(params["embed"], ids) * math.sqrt(cfg.d_model)
        x = x + sinusoid_positions(ids.shape[1], cfg.d_model)
        return self.drop(None, x, key=key, training=training)

    def _mb_extras(self, tree):
        """Microbatch (B, ...) extras to (M, mb, ...) + matching specs."""
        from paddle_tpu.parallel import pipeline as pp_lib

        return pp_lib.microbatch_extras(tree, self.cfg.pp_microbatches)

    def encode(self, params, src_ids, *, key=None, training=False,
               pipelined=None):
        cfg = self.cfg
        if pipelined is None:
            pipelined = cfg.pipeline
        src_mask = src_ids != cfg.pad_id
        bias = ops_attn.make_padding_bias(src_mask)
        keys = ([None] * (cfg.num_encoder_layers + 1) if key is None
                else list(jax.random.split(key, cfg.num_encoder_layers + 1)))
        x = self._embed(params, src_ids, keys[0], training)
        if pipelined:
            from paddle_tpu.parallel import pipeline as pp_lib

            extras, extras_spec = self._mb_extras(bias)
            x = pp_lib.gpipe_layer_stack(
                lambda lp, h, extra, k: self.encoder[0](
                    lp, h, bias=extra, key=k, training=training),
                [params["encoder"][str(i)]
                 for i in range(cfg.num_encoder_layers)],
                x, num_microbatches=cfg.pp_microbatches,
                layer_keys=keys[1:], extras=extras,
                extras_spec=extras_spec, schedule=cfg.pp_schedule,
                num_circuits=cfg.pp_circuits)
        else:
            for i, layer in enumerate(self.encoder):
                x = layer(params["encoder"][str(i)], x, bias=bias,
                          key=keys[i + 1], training=training)
        if cfg.pre_ln:
            x = self.enc_ln(params["enc_ln"], x)
        return x, bias

    def decode(self, params, tgt_ids, memory, memory_bias, *, key=None,
               training=False, pipelined=None):
        cfg = self.cfg
        if pipelined is None:
            pipelined = cfg.pipeline
        keys = ([None] * (cfg.num_decoder_layers + 1) if key is None
                else list(jax.random.split(key, cfg.num_decoder_layers + 1)))
        x = self._embed(params, tgt_ids, keys[0], training)
        if pipelined:
            from paddle_tpu.parallel import pipeline as pp_lib

            # the encoder memory + its padding bias ride the ring with
            # each microbatch (every decoder stage cross-attends them)
            extras, extras_spec = self._mb_extras(
                {"memory": memory, "bias": memory_bias})
            x = pp_lib.gpipe_layer_stack(
                lambda lp, h, extra, k: self.decoder[0](
                    lp, h, extra["memory"], cross_bias=extra["bias"],
                    key=k, training=training),
                [params["decoder"][str(i)]
                 for i in range(cfg.num_decoder_layers)],
                x, num_microbatches=cfg.pp_microbatches,
                layer_keys=keys[1:], extras=extras,
                extras_spec=extras_spec, schedule=cfg.pp_schedule,
                num_circuits=cfg.pp_circuits)
        else:
            for i, layer in enumerate(self.decoder):
                x = layer(params["decoder"][str(i)], x, memory,
                          cross_bias=memory_bias, key=keys[i + 1],
                          training=training)
        if cfg.pre_ln:
            x = self.dec_ln(params["dec_ln"], x)
        # tied output projection
        return jnp.einsum("bsd,vd->bsv", x, params["embed"]["weight"])

    def forward(self, params, src_ids, tgt_ids, *, key=None, training=False):
        """Teacher-forced logits: (B, S_tgt, V)."""
        k1 = k2 = None
        if key is not None:
            k1, k2 = jax.random.split(key)
        memory, memory_bias = self.encode(params, src_ids, key=k1,
                                          training=training)
        return self.decode(params, tgt_ids, memory, memory_bias, key=k2,
                           training=training)

    def loss(self, params, src_ids, tgt_in, tgt_out, *, key=None,
             training=True):
        """tgt_in = [BOS, y...], tgt_out = [y..., EOS]; pad_id positions of
        tgt_out are masked from the loss. Label smoothing per cfg."""
        cfg = self.cfg
        logits = self.forward(params, src_ids, tgt_in, key=key,
                              training=training)
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, tgt_out[..., None], axis=-1)[..., 0]
        if cfg.label_smoothing > 0:
            eps = cfg.label_smoothing
            smooth = -logp.mean(axis=-1)
            nll = (1 - eps) * nll + eps * smooth
        mask = (tgt_out != cfg.pad_id).astype(jnp.float32)
        denom = jnp.maximum(mask.sum(), 1.0)
        loss = (nll * mask).sum() / denom
        tok_acc = ((logits.argmax(-1) == tgt_out) * mask).sum() / denom
        return loss, {"token_acc": tok_acc}

    # -- packed variable-length training (data/packing.py) ----------------
    #
    # Fluid trains ragged WMT batches on LoD tensors; the TPU-native path
    # packs many pairs into fixed (rows, S) slabs: segment ids gate
    # attention (within-segment only; row-causality x same-segment =
    # per-sequence causality since segments are contiguous), per-segment
    # positions drive the sinusoid embedding, and shapes come from a
    # bucket ladder so jit compiles O(#buckets) programs.

    def _embed_packed(self, params, ids, pos, key=None, training=False):
        cfg = self.cfg
        x = self.embed(params["embed"], ids) * math.sqrt(cfg.d_model)
        # per-segment positions are < the row length, so size the table by
        # the packed bucket too (jnp.take would silently CLAMP positions
        # past a too-small table)
        table = sinusoid_positions(max(cfg.max_len, ids.shape[1]),
                                   cfg.d_model)
        x = x + jnp.take(table, pos, axis=0)
        return self.drop(None, x, key=key, training=training)

    # NOTE: the packed-varlen path below intentionally runs the stacks
    # sequentially even with cfg.pipeline=True — packed slabs already
    # keep utilization high without microbatch scheduling, and a
    # pipelined packed path would need per-microbatch segment bias
    # plumbing. Pipeline + packing composition is future work; the
    # config docstring documents the caveat.
    def encode_packed(self, params, src, src_seg, src_pos, *, key=None,
                      training=False):
        from paddle_tpu.ops import sequence as seq_ops

        cfg = self.cfg
        bias = seq_ops.make_segment_attention_bias(src_seg)
        keys = ([None] * (cfg.num_encoder_layers + 1) if key is None
                else list(jax.random.split(key, cfg.num_encoder_layers + 1)))
        x = self._embed_packed(params, src, src_pos, keys[0], training)
        for i, layer in enumerate(self.encoder):
            x = layer(params["encoder"][str(i)], x, bias=bias,
                      key=keys[i + 1], training=training)
        if cfg.pre_ln:
            x = self.enc_ln(params["enc_ln"], x)
        return x

    def loss_packed(self, params, src, src_seg, src_pos, tgt_in, tgt_out,
                    tgt_seg, tgt_pos, *, key=None, training=True):
        """Packed teacher-forced loss; token-SUM and count are also
        returned so callers can aggregate exactly across batches."""
        from paddle_tpu.ops import sequence as seq_ops

        cfg = self.cfg
        k1 = k2 = None
        if key is not None:
            k1, k2 = jax.random.split(key)
        memory = self.encode_packed(params, src, src_seg, src_pos, key=k1,
                                    training=training)
        # decoder self: same segment (the layer's causal=True supplies
        # row-causality); cross: target segment matches source segment,
        # padding (seg 0) queries see nothing real
        self_bias = seq_ops.make_segment_attention_bias(tgt_seg)
        cross_bias = seq_ops.make_segment_attention_bias(tgt_seg, src_seg)

        keys = ([None] * (cfg.num_decoder_layers + 1) if k2 is None
                else list(jax.random.split(k2, cfg.num_decoder_layers + 1)))
        x = self._embed_packed(params, tgt_in, tgt_pos, keys[0], training)
        for i, layer in enumerate(self.decoder):
            x = layer(params["decoder"][str(i)], x, memory,
                      self_bias=self_bias, cross_bias=cross_bias,
                      key=keys[i + 1], training=training)
        if cfg.pre_ln:
            x = self.dec_ln(params["dec_ln"], x)
        logits = jnp.einsum("bsd,vd->bsv", x, params["embed"]["weight"])

        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, tgt_out[..., None], axis=-1)[..., 0]
        if cfg.label_smoothing > 0:
            eps = cfg.label_smoothing
            nll = (1 - eps) * nll + eps * (-logp.mean(axis=-1))
        mask = (tgt_seg > 0).astype(jnp.float32)
        tok_sum = (nll * mask).sum()
        tok_count = mask.sum()
        loss = tok_sum / jnp.maximum(tok_count, 1.0)
        return loss, {"token_sum": tok_sum, "token_count": tok_count}

    # ---- cached incremental decoding ------------------------------------

    def _decode_state(self, params, memory, max_len, beam_expand=1):
        """Per-layer state for cached decoding: empty self-attention KV
        buffers + cross-attention heads precomputed ONCE from the
        UNexpanded ``memory`` and then repeated ``beam_expand`` times
        (beam search must not pay beam_size x the kv projections)."""
        cfg = self.cfg
        dh = cfg.d_model // cfg.num_heads
        dtype = memory.dtype
        batch = memory.shape[0] * beam_expand
        caches, cross = [], []
        for i, layer in enumerate(self.decoder):
            z = jnp.zeros((batch, cfg.num_heads, max_len, dh), dtype)
            caches.append((z, z))
            k, v = layer.cross_attn.cross_kv(
                params["decoder"][str(i)]["cross_attn"], memory)
            if beam_expand > 1:
                k = jnp.repeat(k, beam_expand, axis=0)
                v = jnp.repeat(v, beam_expand, axis=0)
            cross.append((k, v))
        return caches, cross

    def _cached_step(self, params, tok, t, caches, cross, memory_bias,
                     table_len):
        """tok (B,) at position ``t`` -> (logits (B, V), new caches)."""
        cfg = self.cfg
        x = self.embed(params["embed"], tok[:, None]) * math.sqrt(
            cfg.d_model)
        # size the table by the caller's horizon: dynamic_index CLAMPS
        # out-of-range t, which would silently reuse the last position
        # (same guard as _embed_packed)
        table = sinusoid_positions(max(cfg.max_len, table_len),
                                   cfg.d_model)
        x = x + jax.lax.dynamic_index_in_dim(table, t, keepdims=True)
        new_caches = []
        for i, layer in enumerate(self.decoder):
            x, kv = layer.decode_step(
                params["decoder"][str(i)], x, t, caches[i], cross[i],
                cross_bias=memory_bias)
            new_caches.append(kv)
        if cfg.pre_ln:
            x = self.dec_ln(params["dec_ln"], x)
        logits = jnp.einsum("bd,vd->bv", x[:, 0],
                            params["embed"]["weight"])
        return logits, new_caches

    def greedy_decode(self, params, src_ids, max_len=None,
                      use_cache=True):
        """Greedy generation (≙ reference beam_search with beam=1).
        ``use_cache=True`` (default) decodes through per-layer self-attn
        KV caches + precomputed cross-attn memory heads — O(S) per
        token; the uncached path refeeds the whole prefix each step."""
        cfg = self.cfg
        max_len = max_len or cfg.max_len
        b = src_ids.shape[0]
        # inference: always the sequential stacks — the pipelined path
        # needs a pp mesh + microbatch-divisible batch (training shape)
        memory, memory_bias = self.encode(params, src_ids,
                                          pipelined=False)
        tgt = jnp.full((b, max_len), cfg.pad_id, jnp.int32)
        tgt = tgt.at[:, 0].set(cfg.bos_id)
        done = jnp.zeros((b,), bool)

        if use_cache:
            caches, cross = self._decode_state(params, memory, max_len)

            def cond(carry):
                t, _, done, _ = carry
                return (t < max_len - 1) & ~jnp.all(done)

            def body(carry):
                t, tgt, done, caches = carry
                logits, caches = self._cached_step(
                    params, tgt[:, t], t, caches, cross, memory_bias,
                    max_len)
                nxt = logits.argmax(-1).astype(jnp.int32)
                nxt = jnp.where(done, cfg.pad_id, nxt)
                tgt = tgt.at[:, t + 1].set(nxt)
                done = done | (nxt == cfg.eos_id)
                return t + 1, tgt, done, caches

            _, tgt, _, _ = jax.lax.while_loop(
                cond, body, (0, tgt, done, caches))
            return tgt

        def cond(carry):
            t, _, done = carry
            return (t < max_len - 1) & ~jnp.all(done)

        def body(carry):
            t, tgt, done = carry
            logits = self.decode(params, tgt, memory, memory_bias,
                                 pipelined=False)
            nxt = logits[:, t].argmax(-1).astype(jnp.int32)
            nxt = jnp.where(done, cfg.pad_id, nxt)
            tgt = tgt.at[:, t + 1].set(nxt)
            done = done | (nxt == cfg.eos_id)
            return t + 1, tgt, done

        _, tgt, _ = jax.lax.while_loop(cond, body, (0, tgt, done))
        return tgt

    def beam_search_decode(self, params, src_ids, *, beam_size: int = 4,
                           max_len: Optional[int] = None,
                           length_penalty: float = 0.6,
                           use_cache: bool = True):
        """Beam search (reference ``beam_search_op`` + ``layers.beam_search``
        machine-translation path). GNMT-style length normalization
        ((5+len)/6)^alpha. Returns (best_ids (B, T), best_scores (B,)).

        ``use_cache=True`` (default) decodes through beam-expanded KV
        caches, reordered alongside the beams at every step — the
        reference's cached beam decoder; the uncached path refeeds
        every prefix each step."""
        from paddle_tpu.ops import beam_search as bs
        cfg = self.cfg
        max_len = max_len or cfg.max_len
        b = src_ids.shape[0]
        k = beam_size
        v = cfg.vocab_size

        memory, memory_bias = self.encode(params, src_ids,
                                          pipelined=False)
        # expand memory to beams: (B*K, S, D)
        mem = jnp.repeat(memory, k, axis=0)
        mem_bias = jnp.repeat(memory_bias, k, axis=0)

        tgt = jnp.full((b, k, max_len), cfg.pad_id, jnp.int32)
        tgt = tgt.at[:, :, 0].set(cfg.bos_id)
        scores, done = bs.beam_init(b, k)

        def penalty(length):
            return ((5.0 + length) / 6.0) ** length_penalty

        def select(logits_t, t, tgt, scores, done):
            """Beam bookkeeping via the reusable ops.beam_search_step;
            logits_t (B*K, V) at step t. Returns (tgt, scores, done,
            src_beam)."""
            logp = jax.nn.log_softmax(logits_t.astype(jnp.float32), -1)
            tok, scores, done, src_beam = bs.beam_search_step(
                logp.reshape(b, k, v), scores, done,
                eos_id=cfg.eos_id, pad_id=cfg.pad_id)
            tgt = jnp.take_along_axis(tgt, src_beam[..., None], axis=1)
            tgt = tgt.at[:, :, t + 1].set(tok)
            return tgt, scores, done, src_beam

        if use_cache:
            caches, cross = self._decode_state(params, memory, max_len,
                                               beam_expand=k)

            def body(t, carry):
                tgt, scores, done, caches = carry
                logits, caches = self._cached_step(
                    params, tgt.reshape(b * k, max_len)[:, t], t,
                    caches, cross, mem_bias, max_len)
                tgt, scores, done, src_beam = select(
                    logits, t, tgt, scores, done)
                # KV caches ride with their beams (flat B*K rows)
                caches = bs.gather_beams(caches, src_beam)
                return tgt, scores, done, caches

            tgt, scores, done, _ = jax.lax.fori_loop(
                0, max_len - 1, body, (tgt, scores, done, caches))
        else:
            def body(t, carry):
                tgt, scores, done = carry
                logits = self.decode(params, tgt.reshape(b * k, max_len),
                                     mem, mem_bias,
                                     pipelined=False)[:, t]    # (B*K, V)
                tgt, scores, done, _ = select(logits, t, tgt, scores,
                                              done)
                return tgt, scores, done

            tgt, scores, done = jax.lax.fori_loop(
                0, max_len - 1, body, (tgt, scores, done))
        # length-normalized final ranking
        lengths = (tgt != cfg.pad_id).sum(-1).astype(jnp.float32)
        norm = scores / penalty(lengths)
        best = jnp.argmax(norm, axis=1)
        best_ids = jnp.take_along_axis(
            tgt, best[:, None, None], axis=1)[:, 0]
        best_scores = jnp.take_along_axis(norm, best[:, None], 1)[:, 0]
        return best_ids, best_scores
