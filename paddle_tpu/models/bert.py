"""BERT model family (flagship config for the north-star benchmark).

Reference mapping: BERT-base pretraining is BASELINE.json config[2]
("models/PaddleNLP — matmul/layer_norm/softmax hot path"); the reference
framework builds it from ``fluid.layers`` primitives (fc/layer_norm/matmul/
softmax, ``layers/nn.py``). Here it is a Layer over the Pallas-flash
transformer stack (``nn/transformer.py``) with TP/SP sharding hints baked
into every projection, so the same model object runs 1-chip or over a
dp×fsdp×tp×sp mesh unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Dropout, Embedding, LayerNorm, Linear
from paddle_tpu.nn.module import Layer, LayerList, StackedLayers
from paddle_tpu.nn.transformer import ACT_SPEC, TransformerEncoderLayer, _constrain
from paddle_tpu.ops import activation as ops_act
from paddle_tpu.ops import attention as ops_attn


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1
    attn_dropout: float = 0.1
    pre_ln: bool = False
    attn_impl: str = "auto"
    # pipeline parallelism: run the encoder stack through the GPipe
    # schedule over the "pp" mesh axis (parallel/pipeline.py), cutting the
    # L layers into pp stages and streaming pp_microbatches through them.
    # Embeddings/heads stay outside the pipelined middle.
    pipeline: bool = False
    pp_microbatches: int = 2
    # "gpipe", or "circular" (interleaved 1F1B; pp_circuits virtual
    # stages per device — smaller bubble, see
    # parallel.pipeline.pipeline_bubble_fraction)
    pp_schedule: str = "gpipe"
    pp_circuits: int = 1
    # params already hold the circular schedule's interleaved layer order
    # (convert once with parallel.pipeline.interleave_stack on the
    # encoder stack) — skips the per-step cross-device weight reshuffle
    pp_pre_interleaved: bool = False
    # scan-over-layers param layout: encoder params stored as stacked
    # (L, ...) leaves sharded over "pp" from init — one compiled block
    # (faster compile), and pipeline stages own their rows by placement
    # (no in-graph stack/reshard). Defaults on when pipeline is on.
    # NOTE: this changes the checkpoint tree layout; convert older
    # per-layer checkpoints with stack_encoder_params / unstack_.
    stacked_layers: Optional[bool] = None

    def __post_init__(self):
        if self.stacked_layers is None:
            self.stacked_layers = self.pipeline

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def large(cls, **kw):
        return cls(hidden_size=1024, num_layers=24, num_heads=16,
                   ffn_size=4096, **kw)

    @classmethod
    def tiny(cls, **kw):
        """Test-size config."""
        kw.setdefault("vocab_size", 128)
        kw.setdefault("hidden_size", 32)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 2)
        kw.setdefault("ffn_size", 64)
        kw.setdefault("max_position", 64)
        return cls(**kw)


def stack_encoder_params(params, num_layers: int):
    """Convert a LayerList-layout BERT param tree ("encoder"/"0"/... per
    layer) to the stacked scan-over-layers layout — for loading
    checkpoints saved before ``stacked_layers`` (or by non-stacked
    configs) into a stacked model. (Generic form for other models:
    parallel.pipeline.stack_params_at.)"""
    from paddle_tpu.parallel.pipeline import stack_params_at
    return stack_params_at(params, ("bert", "encoder"), num_layers)


def unstack_encoder_params(params, num_layers: int):
    """Inverse of :func:`stack_encoder_params`."""
    from paddle_tpu.parallel.pipeline import unstack_params_at
    return unstack_params_at(params, ("bert", "encoder"), num_layers)


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word = Embedding(cfg.vocab_size, cfg.hidden_size,
                              weight_init=I.normal(0.0, 0.02))
        self.position = Embedding(cfg.max_position, cfg.hidden_size,
                                  weight_init=I.normal(0.0, 0.02),
                                  sharding=None)
        self.token_type = Embedding(cfg.type_vocab_size, cfg.hidden_size,
                                    weight_init=I.normal(0.0, 0.02),
                                    sharding=None)
        self.ln = LayerNorm(cfg.hidden_size)
        self.drop = Dropout(cfg.dropout)

    def forward(self, params, input_ids, token_type_ids=None, *,
                key=None, training=False):
        s = input_ids.shape[1]
        pos = jnp.arange(s, dtype=jnp.int32)[None, :]
        x = self.word(params["word"], input_ids)
        x = x + self.position(params["position"], pos)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + self.token_type(params["token_type"], token_type_ids)
        x = self.ln(params["ln"], x)
        return self.drop(None, x, key=key, training=training)


class BertModel(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)

        def make_layer():
            return TransformerEncoderLayer(
                cfg.hidden_size, cfg.num_heads, cfg.ffn_size,
                dropout=cfg.dropout, attn_dropout=cfg.attn_dropout,
                pre_ln=cfg.pre_ln, attn_impl=cfg.attn_impl)

        if cfg.stacked_layers:
            self.encoder = StackedLayers(make_layer(), cfg.num_layers)
        else:
            self.encoder = LayerList(
                [make_layer() for _ in range(cfg.num_layers)])
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size,
                             sharding=None)

    def forward(self, params, input_ids, token_type_ids=None,
                attention_mask=None, *, key=None, training=False):
        """Returns (sequence_output (B,S,D), pooled_output (B,D))."""
        keys = [None] * (self.cfg.num_layers + 1)
        if key is not None:
            keys = list(jax.random.split(key, self.cfg.num_layers + 1))
        bias = None
        if attention_mask is not None:
            bias = ops_attn.make_padding_bias(attention_mask)
        x = self.embeddings(params["embeddings"], input_ids, token_type_ids,
                            key=keys[0], training=training)
        x = _constrain(x, ACT_SPEC)
        if self.cfg.pipeline:
            x = self._encoder_pipelined(params, x, bias, keys[1:], training)
        elif self.cfg.stacked_layers:
            lkeys = (jnp.stack(keys[1:]) if keys[1] is not None else None)
            x = self.encoder(params["encoder"], x, layer_keys=lkeys,
                             bias=bias, training=training)
        else:
            for i, layer in enumerate(self.encoder):
                x = layer(params["encoder"][str(i)], x, bias=bias,
                          key=keys[i + 1], training=training)
        pooled = jnp.tanh(self.pooler(params["pooler"], x[:, 0]))
        return x, pooled

    def _encoder_pipelined(self, params, x, bias, layer_keys, training):
        """GPipe the encoder stack over "pp" (PipelineOptimizer analog,
        optimizer.py:2931): per-layer params are stacked to (L, ...) leaves
        sharded over the stage axis; the attention bias rides the ring as
        a per-microbatch extra."""
        from paddle_tpu.parallel import pipeline as pp_lib

        cfg = self.cfg
        M = cfg.pp_microbatches
        extras = extras_spec = None
        if bias is not None:
            extras, extras_spec = pp_lib.microbatch_extras(bias, M)

        if cfg.stacked_layers:
            block_layer = self.encoder.template
            enc_params = params["encoder"]       # pre-stacked (L, ...)
        else:
            block_layer = self.encoder[0]
            enc_params = [params["encoder"][str(i)]
                          for i in range(cfg.num_layers)]
        return pp_lib.gpipe_layer_stack(
            lambda lp, h, extra, k: block_layer(
                lp, h, bias=extra, key=k, training=training),
            enc_params,
            x, num_microbatches=M, layer_keys=layer_keys,
            extras=extras, extras_spec=extras_spec,
            schedule=cfg.pp_schedule, num_circuits=cfg.pp_circuits,
            pre_interleaved=cfg.pp_pre_interleaved)


class BertPretrainingHeads(Layer):
    """MLM head (transform + tied-embedding decoder) + NSP head."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                sharding=None)
        self.ln = LayerNorm(cfg.hidden_size)
        self.decoder_bias = self.create_parameter(
            "decoder_bias", (cfg.vocab_size,), initializer=I.zeros,
            sharding=P("tp"))
        self.nsp = Linear(cfg.hidden_size, 2, sharding=None)

    def forward(self, params, sequence_output, pooled_output, word_table):
        h = ops_act.gelu(self.transform(params["transform"], sequence_output))
        h = self.ln(params["ln"], h)
        mlm_logits = jnp.einsum("bsd,vd->bsv", h, word_table) \
            + params["decoder_bias"]
        nsp_logits = self.nsp(params["nsp"], pooled_output)
        return mlm_logits, nsp_logits


class BertForPretraining(Layer):
    """BERT with MLM + NSP losses (PaddleNLP pretraining recipe parity)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg)
        self.heads = BertPretrainingHeads(cfg)

    def forward(self, params, input_ids, token_type_ids=None,
                attention_mask=None, *, key=None, training=False):
        seq, pooled = self.bert(params["bert"], input_ids, token_type_ids,
                                attention_mask, key=key, training=training)
        word_table = params["bert"]["embeddings"]["word"]["weight"]
        return self.heads(params["heads"], seq, pooled, word_table)

    def loss(self, params, input_ids, token_type_ids, attention_mask,
             mlm_labels, mlm_mask, nsp_labels, *, key=None, training=True):
        """mlm_labels: (B,S) target ids; mlm_mask: (B,S) 1.0 where masked;
        nsp_labels: (B,). Returns (loss, metrics)."""
        mlm_logits, nsp_logits = self.forward(
            params, input_ids, token_type_ids, attention_mask,
            key=key, training=training)
        mlm_lp = jax.nn.log_softmax(mlm_logits.astype(jnp.float32), axis=-1)
        mlm_nll = -jnp.take_along_axis(
            mlm_lp, mlm_labels[..., None], axis=-1)[..., 0]
        denom = jnp.maximum(mlm_mask.sum(), 1.0)
        mlm_loss = (mlm_nll * mlm_mask).sum() / denom
        nsp_lp = jax.nn.log_softmax(nsp_logits.astype(jnp.float32), axis=-1)
        nsp_loss = -jnp.take_along_axis(
            nsp_lp, nsp_labels[:, None], axis=-1).mean()
        loss = mlm_loss + nsp_loss
        return loss, {"mlm_loss": mlm_loss, "nsp_loss": nsp_loss}
