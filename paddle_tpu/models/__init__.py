"""Model zoo covering the BASELINE.json configs: LeNet (MNIST), ResNet-50,
BERT-base, Transformer-big, DeepFM (reference model sources:
``python/paddle/fluid/tests/book/`` + PaddleCV/PaddleNLP recipes)."""

from paddle_tpu.models.lenet import LeNet
from paddle_tpu.models.bert import (BertConfig, BertModel, BertForPretraining)
from paddle_tpu.models.resnet import ResNet, ResNet50
from paddle_tpu.models.deepfm import DeepFM
from paddle_tpu.models.transformer import Transformer, TransformerConfig
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.models.book import (LinearRegression, MachineTranslation,
                                    RNNLanguageModel,
                                    RecommenderSystem, SentimentCNN,
                                    SentimentLSTM,
                                    SkipGramNS, Word2Vec)
from paddle_tpu.models.mobilenet import MobileNetV1, MobileNetV2
from paddle_tpu.models.vgg import VGG, VGG16
from paddle_tpu.models.se_resnext import SEResNeXt, SEResNeXt50
from paddle_tpu.models.ssd import SSD, SSDConfig
from paddle_tpu.models.faster_rcnn import (FasterRCNN, FasterRCNNConfig,
                                            MaskRCNN)
from paddle_tpu.models.legacy_cv import (AlexNet, DarkNet53,
                                         DenseNet121, GoogLeNet,
                                         ShuffleNetV2, SqueezeNet)
from paddle_tpu.models.video import C3D, TSN
from paddle_tpu.models.yolov3 import YOLOv3, YOLOv3Config
from paddle_tpu.models.ocr import CRNN
from paddle_tpu.models.gan import (DCGANDiscriminator, DCGANGenerator,
                                   gan_step)

__all__ = ["LeNet", "BertConfig", "BertModel", "BertForPretraining",
           "ResNet", "ResNet50", "DeepFM", "Transformer",
           "TransformerConfig", "GPT", "GPTConfig", "LinearRegression",
           "MachineTranslation", "RNNLanguageModel", "SentimentCNN", "SentimentLSTM", "SkipGramNS", "Word2Vec", "RecommenderSystem",
           "MobileNetV1", "MobileNetV2", "VGG", "VGG16", "SEResNeXt",
           "SEResNeXt50", "AlexNet", "DarkNet53", "DenseNet121", "GoogLeNet", "ShuffleNetV2", "SqueezeNet", "SSD", "SSDConfig", "FasterRCNN", "FasterRCNNConfig", "MaskRCNN", "C3D", "TSN", "YOLOv3", "YOLOv3Config", "CRNN", "DCGANGenerator", "DCGANDiscriminator", "gan_step"]
