"""Model zoo covering the BASELINE.json configs: LeNet (MNIST), ResNet-50,
BERT-base, Transformer-big, DeepFM (reference model sources:
``python/paddle/fluid/tests/book/`` + PaddleCV/PaddleNLP recipes)."""

from paddle_tpu.models.lenet import LeNet
from paddle_tpu.models.bert import (BertConfig, BertModel, BertForPretraining)
from paddle_tpu.models.resnet import ResNet, ResNet50
from paddle_tpu.models.deepfm import DeepFM
from paddle_tpu.models.transformer import Transformer, TransformerConfig
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.models.book import (LinearRegression, RNNLanguageModel,
                                    SentimentLSTM, SkipGramNS, Word2Vec)

__all__ = ["LeNet", "BertConfig", "BertModel", "BertForPretraining",
           "ResNet", "ResNet50", "DeepFM", "Transformer",
           "TransformerConfig", "GPT", "GPTConfig", "LinearRegression",
           "RNNLanguageModel", "SentimentLSTM", "SkipGramNS", "Word2Vec"]
