"""LeNet-5 for MNIST — BASELINE.json config 1 (book/02.recognize_digits,
reference model ``python/paddle/fluid/tests/book/test_recognize_digits.py``
``convolutional_neural_network``: two ``simple_img_conv_pool`` stages then
fc-softmax — built on the same composite here)."""

from __future__ import annotations

from paddle_tpu import nn
from paddle_tpu.nn.nets import SimpleImgConvPool
from paddle_tpu.ops import activation as A
from paddle_tpu.ops import tensor as T


class LeNet(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.conv_pool1 = SimpleImgConvPool(1, 20, 5, pool_size=2,
                                            pool_stride=2, act="relu")
        self.conv_pool2 = SimpleImgConvPool(20, 50, 5, pool_size=2,
                                            pool_stride=2, act="relu")
        self.fc1 = nn.Linear(4 * 4 * 50, 500, sharding=None)
        self.fc2 = nn.Linear(500, num_classes, sharding=None)

    def forward(self, params, x):
        # x: [N, 28, 28, 1] NHWC
        h = self.conv_pool1(params["conv_pool1"], x)      # [N,12,12,20]
        h = self.conv_pool2(params["conv_pool2"], h)      # [N,4,4,50]
        h = T.flatten(h, 1)                               # [N,800]
        h = A.relu(self.fc1(params["fc1"], h))
        return self.fc2(params["fc2"], h)                 # logits
