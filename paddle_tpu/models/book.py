"""Book-example model zoo: the reference's fluid "book" test suite parity.

Reference models (``python/paddle/fluid/tests/book/``):
- ``test_fit_a_line.py``      -> :class:`LinearRegression`
- ``test_word2vec.py``        -> :class:`Word2Vec` (N-gram NLM variant used
  by the book test) + skip-gram negative sampling variant
- ``test_understand_sentiment.py`` -> :class:`SentimentLSTM` (stacked LSTM)
- ``test_rnn_language_model`` (models repo) -> :class:`RNNLanguageModel`
(LeNet/ResNet/BERT/Transformer/DeepFM live in their own modules.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import Embedding, Linear
from paddle_tpu.nn.module import Layer
from paddle_tpu.nn.rnn import LSTM
from paddle_tpu.ops import nn as ops_nn
from paddle_tpu.ops import sequence as seq_ops


class LinearRegression(Layer):
    """fit_a_line: y = xW + b with MSE loss."""

    def __init__(self, in_features=13):
        super().__init__()
        self.fc = Linear(in_features, 1, sharding=None)

    def forward(self, params, x):
        return self.fc(params["fc"], x)[:, 0]

    def loss(self, params, x, y):
        pred = self.forward(params, x)
        return ((pred - y) ** 2).mean(), {}


class Word2Vec(Layer):
    """N-gram neural language model (the book's word2vec recipe: embed N
    context words, concat, hidden layer, softmax over vocab)."""

    def __init__(self, vocab_size, embed_dim=32, context=4, hidden=256):
        super().__init__()
        self.embed = Embedding(vocab_size, embed_dim,
                               weight_init=I.normal(0.0, 0.02))
        self.context = context
        self.fc1 = Linear(context * embed_dim, hidden, sharding=None)
        self.fc2 = Linear(hidden, vocab_size)

    def forward(self, params, context_ids):
        """context_ids: (B, context)."""
        e = self.embed(params["embed"], context_ids)     # (B, C, D)
        h = e.reshape(e.shape[0], -1)
        h = jax.nn.sigmoid(self.fc1(params["fc1"], h))
        return self.fc2(params["fc2"], h)

    def loss(self, params, context_ids, target_ids):
        logits = self.forward(params, context_ids)
        nll = ops_nn.softmax_with_cross_entropy(
            logits, target_ids[:, None]).mean()
        return nll, {}


class SkipGramNS(Layer):
    """Skip-gram with negative sampling (the scalable word2vec)."""

    def __init__(self, vocab_size, embed_dim=64):
        super().__init__()
        self.in_embed = Embedding(vocab_size, embed_dim,
                                  weight_init=I.normal(0.0, 0.02))
        self.out_embed = Embedding(vocab_size, embed_dim,
                                   weight_init=I.zeros)

    def loss(self, params, center, positive, negatives):
        """center (B,), positive (B,), negatives (B, K)."""
        c = self.in_embed(params["in_embed"], center)          # (B, D)
        pos = self.out_embed(params["out_embed"], positive)    # (B, D)
        neg = self.out_embed(params["out_embed"], negatives)   # (B, K, D)
        pos_logit = (c * pos).sum(-1)
        neg_logit = jnp.einsum("bd,bkd->bk", c, neg)
        loss = (jax.nn.softplus(-pos_logit).mean()
                + jax.nn.softplus(neg_logit).sum(-1).mean())
        return loss, {}


class SentimentLSTM(Layer):
    """understand_sentiment: embedding -> stacked LSTM -> pool -> softmax."""

    def __init__(self, vocab_size, num_classes=2, embed_dim=64,
                 hidden=128, num_layers=2):
        super().__init__()
        self.embed = Embedding(vocab_size, embed_dim,
                               weight_init=I.normal(0.0, 0.02))
        self.lstm = LSTM(embed_dim, hidden, num_layers=num_layers)
        self.fc = Linear(self.lstm.output_size, num_classes, sharding=None)

    def forward(self, params, ids, lengths):
        x = self.embed(params["embed"], ids)
        h, _ = self.lstm(params["lstm"], x, lengths)
        pooled = seq_ops.sequence_pool(h, lengths, "max")
        return self.fc(params["fc"], pooled)

    def loss(self, params, ids, lengths, label):
        logits = self.forward(params, ids, lengths)
        nll = ops_nn.softmax_with_cross_entropy(logits, label[:, None]).mean()
        acc = (logits.argmax(-1) == label).mean()
        return nll, {"acc": acc}


class SentimentCNN(Layer):
    """understand_sentiment, conv variant (reference
    ``test_understand_sentiment_conv_new_api.py:38`` ``convolution_net``):
    embedding -> two ``sequence_conv_pool`` branches (filter sizes 3 and 4,
    tanh, sqrt-pool) -> concat -> fc softmax."""

    def __init__(self, vocab_size, num_classes=2, embed_dim=128,
                 hidden=512):
        super().__init__()
        from paddle_tpu.nn.nets import SequenceConvPool
        self.embed = Embedding(vocab_size, embed_dim,
                               weight_init=I.normal(0.0, 0.02))
        self.conv3 = SequenceConvPool(embed_dim, hidden, 3,
                                      act="tanh", pool_type="sqrt")
        self.conv4 = SequenceConvPool(embed_dim, hidden, 4,
                                      act="tanh", pool_type="sqrt")
        self.fc = Linear(2 * hidden, num_classes, sharding=None)

    def forward(self, params, ids, lengths):
        x = self.embed(params["embed"], ids)
        h = jnp.concatenate([self.conv3(params["conv3"], x, lengths),
                             self.conv4(params["conv4"], x, lengths)], -1)
        return self.fc(params["fc"], h)

    def loss(self, params, ids, lengths, label):
        logits = self.forward(params, ids, lengths)
        nll = ops_nn.softmax_with_cross_entropy(logits, label[:, None]).mean()
        acc = (logits.argmax(-1) == label).mean()
        return nll, {"acc": acc}


class RNNLanguageModel(Layer):
    """LSTM LM (PaddleNLP language_model recipe): next-token prediction
    with tied-embedding option."""

    def __init__(self, vocab_size, embed_dim=128, hidden=128, num_layers=2,
                 tie_embeddings=True):
        super().__init__()
        self.embed = Embedding(vocab_size, embed_dim,
                               weight_init=I.normal(0.0, 0.05))
        self.lstm = LSTM(embed_dim, hidden, num_layers=num_layers)
        self.tie = tie_embeddings and hidden == embed_dim
        if not self.tie:
            self.proj = Linear(hidden, vocab_size)

    def forward(self, params, ids, lengths=None):
        x = self.embed(params["embed"], ids)
        h, _ = self.lstm(params["lstm"], x, lengths)
        if self.tie:
            return jnp.einsum("bsd,vd->bsv", h, params["embed"]["weight"])
        return self.proj(params["proj"], h)

    def loss(self, params, ids, targets, lengths=None):
        logits = self.forward(params, ids, lengths)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
        if lengths is not None:
            mask = seq_ops.sequence_mask(lengths, ids.shape[1], jnp.float32)
            denom = jnp.maximum(mask.sum(), 1.0)
            loss = (nll * mask).sum() / denom
            ppl = jnp.exp(loss)
        else:
            loss = nll.mean()
            ppl = jnp.exp(loss)
        return loss, {"ppl": ppl}


class RecommenderSystem(Layer):
    """book/05.recommender_system (test_recommender_system.py): two-tower
    personalized-rating model — user tower (id/gender/age/occupation
    embeddings) and movie tower (id embedding + category multi-hot),
    fused by cosine similarity scaled to the rating range, MSE loss."""

    def __init__(self, n_users=6041, n_movies=3953, n_cat=18, dim=32):
        super().__init__()
        self.user_emb = Embedding(n_users, dim)
        self.gender_emb = Embedding(2, dim // 2)
        self.age_emb = Embedding(7, dim // 2)
        self.occ_emb = Embedding(21, dim // 2)
        self.user_fc = Linear(dim + 3 * (dim // 2), dim, sharding=None)
        self.movie_emb = Embedding(n_movies, dim)
        self.cat_fc = Linear(n_cat, dim // 2, sharding=None)
        self.movie_fc = Linear(dim + dim // 2, dim, sharding=None)

    def forward(self, params, user_id, gender, age, occupation, movie_id,
                categories):
        u = jnp.concatenate([
            self.user_emb(params["user_emb"], user_id),
            self.gender_emb(params["gender_emb"], gender),
            self.age_emb(params["age_emb"], age),
            self.occ_emb(params["occ_emb"], occupation)], -1)
        u = jnp.tanh(self.user_fc(params["user_fc"], u))
        m = jnp.concatenate([
            self.movie_emb(params["movie_emb"], movie_id),
            jnp.tanh(self.cat_fc(params["cat_fc"], categories))], -1)
        m = jnp.tanh(self.movie_fc(params["movie_fc"], m))
        cos = (u * m).sum(-1) / (
            jnp.linalg.norm(u, axis=-1) * jnp.linalg.norm(m, axis=-1)
            + 1e-8)
        return 5.0 * cos                      # scale_op(5) in the book

    def loss(self, params, user_id, gender, age, occupation, movie_id,
             categories, rating, *, training=True, key=None):
        del training, key
        pred = self.forward(params, user_id, gender, age, occupation,
                            movie_id, categories)
        mse = jnp.mean((pred - rating) ** 2)
        return mse, {"mae": jnp.mean(jnp.abs(pred - rating))}


class MachineTranslation(Layer):
    """book/08.machine_translation (reference
    ``test_machine_translation.py:40-160``): embedding -> tanh fc -> LSTM
    encoder whose final hidden state seeds a plain-RNN decoder
    (``state = tanh(fc([word_emb, state]))``, vocab softmax), decoded
    with the reusable ``ops.beam_search`` ops — the lifted analogs of the
    reference's ``pd.beam_search``/``pd.beam_search_decode`` graph ops.
    Demonstrates the parent-pointer backtracking style (the transformer's
    cached decoder shows the in-loop reorder style)."""

    def __init__(self, src_vocab, trg_vocab, embed_dim=32, hidden=32,
                 bos_id=1, eos_id=2, pad_id=0):
        super().__init__()
        from paddle_tpu.nn.rnn import RNN, LSTMCell
        self.src_embed = Embedding(src_vocab, embed_dim,
                                   weight_init=I.normal(0.0, 0.02))
        self.trg_embed = Embedding(trg_vocab, embed_dim,
                                   weight_init=I.normal(0.0, 0.02))
        self.enc_fc = Linear(embed_dim, hidden, sharding=None)
        self.encoder = RNN(LSTMCell(hidden, hidden))
        self.dec_fc = Linear(embed_dim + hidden, hidden, sharding=None)
        self.out = Linear(hidden, trg_vocab, sharding=None)
        self.bos_id, self.eos_id, self.pad_id = bos_id, eos_id, pad_id

    def encode(self, params, src_ids, src_lengths):
        x = jnp.tanh(self.enc_fc(params["enc_fc"],
                                 self.src_embed(params["src_embed"],
                                                src_ids)))
        _, (h, _) = self.encoder(params["encoder"], x, src_lengths)
        return h                                             # (B, H)

    def _dec_step(self, params, state, emb):
        state = jnp.tanh(self.dec_fc(
            params["dec_fc"], jnp.concatenate([emb, state], -1)))
        return state, self.out(params["out"], state)

    def forward(self, params, src_ids, src_lengths, trg_ids):
        """Teacher-forced logits (B, T, V) for trg_ids (B, T) inputs."""
        ctx = self.encode(params, src_ids, src_lengths)
        emb = self.trg_embed(params["trg_embed"], trg_ids)   # (B, T, E)

        def scan_fn(state, emb_t):
            state, logits = self._dec_step(params, state, emb_t)
            return state, logits

        _, logits = jax.lax.scan(scan_fn, ctx,
                                 jnp.swapaxes(emb, 0, 1))
        return jnp.swapaxes(logits, 0, 1)

    def loss(self, params, src_ids, src_lengths, trg_in, trg_out,
             trg_lengths):
        logits = self.forward(params, src_ids, src_lengths, trg_in)
        nll = ops_nn.softmax_with_cross_entropy(logits, trg_out[..., None])
        mask = seq_ops.sequence_mask(trg_lengths, trg_in.shape[1],
                                     logits.dtype)
        return (nll[..., 0] * mask).sum() / jnp.maximum(mask.sum(), 1.0), {}

    def beam_search_translate(self, params, src_ids, src_lengths, *,
                              beam_size=4, max_len=16,
                              length_penalty=0.0):
        """Beam decode via ops.beam_search_step / gather_beams /
        beam_search_decode. Returns (seqs (B, K, max_len+1) starting with
        BOS, scores (B, K)), best-first."""
        from paddle_tpu.ops import beam_search as bs
        b = src_ids.shape[0]
        k = beam_size
        ctx = self.encode(params, src_ids, src_lengths)
        state = jnp.repeat(ctx[:, None, :], k, axis=1)       # (B, K, H)
        scores, done = bs.beam_init(b, k)
        tok = jnp.full((b, k), self.bos_id, jnp.int32)

        def step(carry, _):
            tok, state, scores, done = carry
            emb = self.trg_embed(params["trg_embed"], tok)   # (B, K, E)
            h = state.reshape(b * k, -1)
            h, logits = self._dec_step(params, h,
                                       emb.reshape(b * k, -1))
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            tok, scores, done, parent = bs.beam_search_step(
                logp.reshape(b, k, -1), scores, done,
                eos_id=self.eos_id, pad_id=self.pad_id)
            state = bs.gather_beams(h.reshape(b, k, -1), parent)
            return (tok, state, scores, done), (tok, parent)

        (_, _, scores, _), (toks, parents) = jax.lax.scan(
            step, (tok, state, scores, done), None, length=max_len)
        toks = jnp.moveaxis(toks, 0, 1)                      # (B, T, K)
        parents = jnp.moveaxis(parents, 0, 1)
        return bs.beam_search_decode(
            toks, parents, scores, eos_id=self.eos_id,
            pad_id=self.pad_id, bos_id=self.bos_id,
            length_penalty=length_penalty)


class LabelSemanticRoles(Layer):
    """book/07.label_semantic_roles (test_label_semantic_roles.py): SRL
    tagger — word + predicate(+mark) embeddings -> stacked BiLSTM ->
    per-token tag emissions -> linear-chain CRF loss, Viterbi decode.
    The reference's 8-direction db-lstm becomes a standard deep BiLSTM;
    the CRF comes from ``ops.crf`` (linear_chain_crf_op parity)."""

    def __init__(self, vocab_size, num_tags, *, dim=32, hidden=32,
                 depth=2):
        super().__init__()
        self.word_emb = Embedding(vocab_size, dim)
        self.pred_emb = Embedding(vocab_size, dim)
        self.mark_emb = Embedding(2, dim // 2)
        self.lstm = LSTM(2 * dim + dim // 2, hidden, num_layers=depth,
                         bidirectional=True)
        self.fc = Linear(self.lstm.output_size, num_tags, sharding=None)
        self.transition = self.create_parameter(
            "transition", (num_tags, num_tags), initializer=I.zeros)
        self.start = self.create_parameter("start", (num_tags,),
                                           initializer=I.zeros)
        self.stop = self.create_parameter("stop", (num_tags,),
                                          initializer=I.zeros)

    def emissions(self, params, words, predicate, mark, lengths):
        x = jnp.concatenate([
            self.word_emb(params["word_emb"], words),
            self.pred_emb(params["pred_emb"],
                          jnp.broadcast_to(predicate[:, None],
                                           words.shape)),
            self.mark_emb(params["mark_emb"], mark)], -1)
        h, _ = self.lstm(params["lstm"], x, lengths)
        return self.fc(params["fc"], h)

    def loss(self, params, words, predicate, mark, labels, lengths, *,
             training=True, key=None):
        del training, key
        from paddle_tpu.ops import crf as crf_ops
        em = self.emissions(params, words, predicate, mark, lengths)
        nll = crf_ops.linear_chain_crf(
            em, labels, lengths, params["transition"],
            start=params["start"], stop=params["stop"])
        return nll.mean(), {}

    def decode(self, params, words, predicate, mark, lengths):
        from paddle_tpu.ops import crf as crf_ops
        em = self.emissions(params, words, predicate, mark, lengths)
        return crf_ops.crf_decoding(em, params["transition"], lengths,
                                    start=params["start"],
                                    stop=params["stop"])
