"""ResNet family (ResNet-18/34/50/101/152) — BASELINE.json config[1].

Reference model: PaddleCV image_classification ResNet-50 (built on fluid
``layers/nn.py`` conv2d:2417 + batch_norm:3871). TPU-native design: NHWC
layout end-to-end (the TPU conv layout; the reference uses NCHW for cuDNN),
BatchNorm running stats through the functional state tape, bf16-friendly
(all convs feed the MXU).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layers import BatchNorm, Conv2D, Linear, Pool2D
from paddle_tpu.nn.module import Layer, LayerList


class ConvBNLayer(Layer):
    def __init__(self, in_ch, out_ch, kernel, stride=1, groups=1,
                 act=None):
        super().__init__()
        self.conv = Conv2D(in_ch, out_ch, kernel, stride=stride,
                           padding=(kernel - 1) // 2, groups=groups,
                           bias=False)
        self.bn = BatchNorm(out_ch)
        self.act = act

    def forward(self, params, x, training=False):
        x = self.conv(params["conv"], x)
        x = self.bn(params["bn"], x, training=training)
        if self.act == "relu":
            x = jax.nn.relu(x)
        elif self.act == "relu6":
            x = jnp.clip(x, 0.0, 6.0)
        elif self.act == "leaky":
            x = jax.nn.leaky_relu(x, 0.1)    # darknet convention
        return x


def space_to_depth(x, block=2):
    """(B, H, W, C) -> (B, H/b, W/b, b*b*C); channel order (r, s, c)."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b, h // block, w // block, block * block * c)


class S2DStemConv(Layer):
    """MXU-friendly ResNet stem: the 7x7/stride-2 conv on 3 channels is
    mathematically re-expressed as a 4x4/stride-1 conv on the 2x2
    space-to-depth-blocked 12-channel input (the MLPerf-style transform —
    identical function, 4x the contraction channels, no strided gather).
    Weights are STORED blocked (4, 4, 4*in_ch, out); use
    :func:`stem_weights_to_s2d` to convert a trained 7x7 checkpoint."""

    def __init__(self, in_ch, out_ch):
        super().__init__()
        # fan_in of the equivalent 7x7 conv (49 taps, not 16*4): keeps the
        # init distribution of the standard stem
        self.weight = self.create_parameter(
            "weight", (4, 4, 4 * in_ch, out_ch),
            initializer=I.msra_normal(fan_in=in_ch * 49))

    def forward(self, params, x):
        xb = space_to_depth(x, 2)
        return jax.lax.conv_general_dilated(
            xb, params["weight"].astype(xb.dtype), (1, 1),
            ((2, 1), (2, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


def stem_weights_to_s2d(w7):
    """(7, 7, C, O) standard stem weights -> (4, 4, 4C, O) blocked weights
    computing the identical function (pixel (2a+r, 2b+s) lives in blocked
    channel slot (2r+s)*C + c; kernel tap i = 2*ka + r - 1)."""
    k, k2, c, o = w7.shape
    if (k, k2) != (7, 7):
        raise ValueError(f"expected 7x7 stem weights, got {w7.shape}")
    wb = jnp.zeros((4, 4, 4 * c, o), w7.dtype)
    for ka in range(4):
        for r in range(2):
            i = 2 * ka + r - 1
            if not 0 <= i <= 6:
                continue
            for kb in range(4):
                for s in range(2):
                    j = 2 * kb + s - 1
                    if not 0 <= j <= 6:
                        continue
                    sl = (r * 2 + s) * c
                    wb = wb.at[ka, kb, sl:sl + c, :].set(w7[i, j])
    return wb


class S2DStem(Layer):
    """ConvBNLayer-shaped wrapper so the param tree keeps the
    stem/{conv,bn} structure (checkpoint layout parity with the 7x7 stem:
    only the conv weight shape differs)."""

    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.conv = S2DStemConv(in_ch, out_ch)
        self.bn = BatchNorm(out_ch)

    def forward(self, params, x, training=False):
        x = self.conv(params["conv"], x)
        x = self.bn(params["bn"], x, training=training)
        return jax.nn.relu(x)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, in_ch, ch, stride=1, downsample=False):
        super().__init__()
        self.conv0 = ConvBNLayer(in_ch, ch, 1, act="relu")
        self.conv1 = ConvBNLayer(ch, ch, 3, stride=stride, act="relu")
        self.conv2 = ConvBNLayer(ch, ch * 4, 1)
        self.has_short = downsample
        if downsample:
            self.short = ConvBNLayer(in_ch, ch * 4, 1, stride=stride)

    def forward(self, params, x, training=False):
        y = self.conv0(params["conv0"], x, training=training)
        y = self.conv1(params["conv1"], y, training=training)
        y = self.conv2(params["conv2"], y, training=training)
        s = self.short(params["short"], x, training=training) \
            if self.has_short else x
        return jax.nn.relu(y + s)


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, in_ch, ch, stride=1, downsample=False):
        super().__init__()
        self.conv0 = ConvBNLayer(in_ch, ch, 3, stride=stride, act="relu")
        self.conv1 = ConvBNLayer(ch, ch, 3)
        self.has_short = downsample
        if downsample:
            self.short = ConvBNLayer(in_ch, ch, 1, stride=stride)

    def forward(self, params, x, training=False):
        y = self.conv0(params["conv0"], x, training=training)
        y = self.conv1(params["conv1"], y, training=training)
        s = self.short(params["short"], x, training=training) \
            if self.has_short else x
        return jax.nn.relu(y + s)


_DEPTHS = {
    18: (BasicBlock, (2, 2, 2, 2)),
    34: (BasicBlock, (3, 4, 6, 3)),
    50: (BottleneckBlock, (3, 4, 6, 3)),
    101: (BottleneckBlock, (3, 4, 23, 3)),
    152: (BottleneckBlock, (3, 8, 36, 3)),
}


class ResNet(Layer):
    """NHWC ResNet. ``width`` scales channel counts (width=64 standard;
    tests use small widths)."""

    def __init__(self, depth=50, num_classes=1000, width=64, in_ch=3,
                 stem="conv7"):
        super().__init__()
        if depth not in _DEPTHS:
            raise ValueError(f"depth must be one of {sorted(_DEPTHS)}")
        if stem not in ("conv7", "s2d"):
            raise ValueError(f"stem must be 'conv7' or 's2d', got {stem!r}")
        block_cls, counts = _DEPTHS[depth]
        self.stem = (S2DStem(in_ch, width) if stem == "s2d" else
                     ConvBNLayer(in_ch, width, 7, stride=2, act="relu"))
        self.pool = Pool2D(3, stride=2, padding=1, pool_type="max")
        blocks = []
        ch_in = width
        for stage, n in enumerate(counts):
            ch = width * (2 ** stage)
            for i in range(n):
                stride = 2 if (i == 0 and stage > 0) else 1
                downsample = (i == 0 and
                              (stride != 1 or ch_in != ch * block_cls.expansion))
                blocks.append(block_cls(ch_in, ch, stride=stride,
                                        downsample=downsample))
                ch_in = ch * block_cls.expansion
        self.blocks = LayerList(blocks)
        self.fc = Linear(ch_in, num_classes,
                         weight_init=I.msra_uniform(fan_in=ch_in),
                         sharding=None)

    def forward(self, params, x, training=False):
        """x: (B, H, W, C) NHWC images -> (B, num_classes) logits."""
        x = self.stem(params["stem"], x, training=training)
        x = self.pool(None, x)
        for i, block in enumerate(self.blocks):
            x = block(params["blocks"][str(i)], x, training=training)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return self.fc(params["fc"], x)

    def loss(self, params, image, label, *, training=True):
        from paddle_tpu.models.common import classification_loss
        return classification_loss(
            self.forward(params, image, training=training), label)


def ResNet50(num_classes=1000, **kw):
    return ResNet(50, num_classes=num_classes, **kw)
