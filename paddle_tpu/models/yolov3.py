"""YOLOv3 detector — PaddleCV yolov3 parity: multi-scale one-stage
detection over a selectable MobileNetV1 or DarkNet53 backbone with
per-scale anchor-masked heads,
trained with ``ops.detection.yolov3_loss`` and decoded with ``yolo_box``
(+ per-class NMS). The reference composes the same ops
(fluid.layers.yolov3_loss / yolo_box, operators/detection/yolov3_loss_op,
yolo_box_op) over a DarkNet body."""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.models.mobilenet import MobileNetV1
from paddle_tpu.models.resnet import ConvBNLayer
from paddle_tpu.nn.layers import Conv2D
from paddle_tpu.nn.module import Layer, LayerList
from paddle_tpu.ops import detection as D


@dataclasses.dataclass
class YOLOv3Config:
    num_classes: int = 80
    # advisory only: loss/detect derive every scale from the actual input
    # tensor, so any (stride-32-divisible) size works at call time
    image_size: int = 416
    backbone_scale: float = 1.0
    # COCO anchors (w, h) pixels; masks pick 3 per scale, big -> small
    anchors: Tuple[Tuple[int, int], ...] = (
        (10, 13), (16, 30), (33, 23), (30, 61), (62, 45), (59, 119),
        (116, 90), (156, 198), (373, 326))
    anchor_masks: Tuple[Tuple[int, ...], ...] = ((6, 7, 8), (3, 4, 5),
                                                (0, 1, 2))
    # "mobilenet" (v1, lightweight PaddleDetection variant) or
    # "darknet53" (the canonical reference backbone)
    backbone: str = "mobilenet"
    # backbone endpoints for strides (32, 16, 8); None = per-backbone
    # default (mobilenet (-1, 10, 4); darknet53 (-1, 22, 13))
    endpoints: Optional[Tuple[int, ...]] = None
    ignore_thresh: float = 0.7

    @classmethod
    def tiny(cls, num_classes=4, image_size=64):
        return cls(num_classes=num_classes, image_size=image_size,
                   backbone_scale=0.125,
                   anchors=((8, 8), (16, 16), (32, 32), (48, 48)),
                   anchor_masks=((2, 3), (0, 1)),
                   endpoints=(-1, 10))


class YOLOv3(Layer):
    """Heads output NCHW (B, A*(5+C), H, W) — the reference layout that
    yolov3_loss/yolo_box consume."""

    def __init__(self, cfg: YOLOv3Config):
        super().__init__()
        self.cfg = cfg
        if cfg.backbone == "darknet53":
            from paddle_tpu.models.legacy_cv import DarkNet53
            self.backbone = DarkNet53(num_classes=1,
                                      scale=cfg.backbone_scale)
            endpoints = (cfg.endpoints if cfg.endpoints is not None
                         else (-1, 22, 13))
        elif cfg.backbone == "mobilenet":
            self.backbone = MobileNetV1(num_classes=1,
                                        scale=cfg.backbone_scale)
            endpoints = (cfg.endpoints if cfg.endpoints is not None
                         else (-1, 10, 4))
        else:
            raise ValueError(f"unknown backbone {cfg.backbone!r}")
        n_blocks = len(self.backbone.blocks)
        self._endpoints = tuple(i if i >= 0 else n_blocks - 1
                                for i in endpoints)

        widths = self.backbone.block_channels
        heads, necks = [], []
        for lvl, ep in enumerate(self._endpoints):
            in_ch = widths[ep]
            a = len(cfg.anchor_masks[lvl])
            necks.append(ConvBNLayer(in_ch, in_ch, 3, act="relu"))
            heads.append(Conv2D(in_ch, a * (5 + cfg.num_classes), 1))
        self.necks = LayerList(necks)
        self.heads = LayerList(heads)

    def forward(self, params, image, training=False):
        """-> list of per-scale raw heads, NCHW (B, A*(5+C), H, W)."""
        _, feats = self.backbone.features(
            params["backbone"], image, training=training,
            endpoints=self._endpoints)
        outs = []
        for i, ep in enumerate(self._endpoints):
            h = self.necks[i](params["necks"][str(i)], feats[ep],
                              training=training)
            y = self.heads[i](params["heads"][str(i)], h)
            outs.append(jnp.transpose(y, (0, 3, 1, 2)))   # NHWC -> NCHW
        return outs

    def loss(self, params, image, gt_boxes, gt_labels, gt_mask, *,
             training=True, key=None):
        """gt_boxes (B, G, 4) normalized (cx, cy, w, h) — the reference's
        yolov3 gt layout."""
        del key
        cfg = self.cfg
        heads = self.forward(params, image, training=training)
        img_w = image.shape[2]                 # NHWC: derive from input
        total = 0.0
        for lvl, head in enumerate(heads):
            downsample = img_w // head.shape[-1]
            total = total + D.yolov3_loss(
                head, gt_boxes, gt_labels, gt_mask,
                anchors=cfg.anchors,
                anchor_mask=cfg.anchor_masks[lvl],
                class_num=cfg.num_classes,
                ignore_thresh=cfg.ignore_thresh,
                downsample_ratio=downsample)
        return total, {}

    def detect(self, params, image, *, score_threshold=0.01,
               nms_threshold=0.45, max_per_class=20):
        """-> per image (boxes (K, 4) pixel xyxy, cls, scores, valid)."""
        cfg = self.cfg
        heads = self.forward(params, image, training=False)
        b, img_h, img_w = image.shape[0], image.shape[1], image.shape[2]
        img_size = jnp.tile(jnp.asarray([[img_h, img_w]], jnp.int32),
                            (b, 1))
        all_boxes, all_scores = [], []
        for lvl, head in enumerate(heads):
            downsample = img_w // head.shape[-1]
            anchors_lvl = [cfg.anchors[i] for i in cfg.anchor_masks[lvl]]
            boxes, scores = D.yolo_box(
                head, img_size, anchors_lvl, cfg.num_classes,
                conf_thresh=score_threshold,
                downsample_ratio=downsample)
            all_boxes.append(boxes)
            all_scores.append(scores)
        boxes = jnp.concatenate(all_boxes, 1)      # (B, P, 4)
        scores = jnp.concatenate(all_scores, 1)    # (B, P, C)

        def one(boxes_i, scores_i):
            cls_ids, idxs, valid = D.multiclass_nms(
                boxes_i, scores_i, iou_threshold=nms_threshold,
                score_threshold=score_threshold,
                max_per_class=max_per_class)
            sel = jnp.where(valid, scores_i[idxs, cls_ids], 0.0)
            return boxes_i[idxs], cls_ids, sel, valid

        return jax.vmap(one)(boxes, scores)
