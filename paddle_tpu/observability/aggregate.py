"""Cross-host metric aggregation: make per-host skew visible from host 0.

In a multi-process (fleet) run every host sees only its own step time /
throughput / data-wait; a single slow host (straggler input pipeline,
thermal throttling, a busy NUMA node) silently drags the whole SPMD
program because the collectives rate-limit to the slowest participant.
``aggregate()`` all-gathers a dict of scalars over the JAX coordination
fabric and returns min/max/mean (+argmin/argmax host index) per key, so
host 0's log line shows the skew directly.

Single-process runs short-circuit to a pure-Python no-op (min == max ==
mean == the local value) — no device work, usable in unit tests and
CPU smoke runs.
"""

from __future__ import annotations

from typing import Dict, Mapping

import numpy as np


def aggregate(values: Mapping[str, float]) -> Dict[str, Dict[str, float]]:
    """All-gather ``{name: scalar}`` across hosts -> per-name stats.

    Every participating host MUST call with the same key set (keys are
    sorted into a dense vector before the collective); the return value
    is identical on every host: ``{name: {min, max, mean, argmin,
    argmax}}`` where argmin/argmax are host (process) indices.
    """
    keys = sorted(values)
    local = np.asarray([float(values[k]) for k in keys], np.float64)
    import jax
    n = jax.process_count()
    if n == 1 or not keys:
        rows = local[None, :]
    else:
        from jax.experimental import multihost_utils
        rows = np.asarray(multihost_utils.process_allgather(local))
        if rows.shape != (n, len(keys)):       # defensive: API drift
            rows = rows.reshape(n, len(keys))
    out: Dict[str, Dict[str, float]] = {}
    for j, k in enumerate(keys):
        col = rows[:, j]
        out[k] = {
            "min": float(col.min()), "max": float(col.max()),
            "mean": float(col.mean()),
            "argmin": int(col.argmin()), "argmax": int(col.argmax()),
        }
    return out


def format_aggregate(stats: Mapping[str, Dict[str, float]]) -> str:
    """One human line per metric: ``name min/mean/max (slowest host)``."""
    parts = []
    for k in sorted(stats):
        s = stats[k]
        parts.append(f"{k} {s['min']:.4g}/{s['mean']:.4g}/{s['max']:.4g}"
                     f" (host{int(s['argmax'])} high)")
    return "[hosts] " + "  ".join(parts)
