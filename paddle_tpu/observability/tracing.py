"""Request-lifecycle tracing: low-overhead spans in a bounded ring.

The aggregate registry (PR 1) answers *that* requests waited; spans
answer *where* — one trace per request, one span per phase (queue,
prefill chunk, decode block, KV pull, snapshot write), with events for
the point decisions in between (admitted, sched_skip, cow_copy, shed).
The design follows the serving discipline everywhere else in the repo:

- **Bounded memory.** Completed spans land in a ring buffer
  (``deque(maxlen=capacity)``); a month-long serving process keeps the
  most recent window, never an unbounded history.
- **Zero cost when disabled.** ``span()`` returns a process-wide no-op
  singleton (no allocation, no clock read); hot paths additionally
  guard their span fan-out behind the ``enabled`` flag so disabled
  tracing is one attribute read per step. Nothing here touches jitted
  code — all instrumentation is host-side around the fixed-shape calls,
  preserving the zero-steady-state-recompile invariant.
- **Thread-correct parentage.** The current-span stack is thread-local,
  so nested spans from the engine thread and background threads (the
  snapshot writer, the streaming applier) attribute to their own
  stacks; explicit ``parent=`` crosses threads when the caller *wants*
  a background span under a foreground one.

Two exporters share the buffer: crash-safe JSONL (one span per line,
flushed per record — the runlog discipline, validated by
:func:`validate_trace_log` / ``tools/check_metrics_log.py --trace``)
and Chrome trace-event JSON (:func:`chrome_trace` /
:meth:`Tracer.export_chrome`) loadable in Perfetto, with span events as
instant markers. ``profiler.record_event`` regions fold into the same
timeline automatically whenever the default tracer is enabled.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

TRACE_SCHEMA_VERSION = 1

# span/trace id 0 is reserved for "none" (the no-op span advertises it)
_NO_ID = 0


class Span:
    """One timed region. Also its own context manager: ``with
    tracer.span("x"):`` pushes/pops the thread-local stack; manual spans
    (``start_span`` … ``finish``) skip the stack for cross-step or
    cross-thread lifecycles (a serving request lives across many
    ``step()`` calls)."""

    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "start", "end", "attrs", "events", "thread", "status",
                 "_on_stack")

    def __init__(self, tracer: "Tracer", trace_id: int, span_id: int,
                 parent_id: int, name: str, start: float,
                 attrs: Optional[Dict[str, Any]]):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = start
        self.end: Optional[float] = None
        self.attrs = attrs or {}
        self.events: List[tuple] = []      # (t, name, attrs)
        self.thread = threading.current_thread().name
        self.status = "ok"
        self._on_stack = False

    @property
    def duration_s(self) -> float:
        return (self.end if self.end is not None
                else self.tracer.now()) - self.start

    def set_attrs(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def add_event(self, name: str, **attrs) -> "Span":
        """Point annotation inside the span (scheduler decisions, CoW
        copies, SLO alerts); exported as Chrome instant events."""
        self.events.append((self.tracer.now(), name, attrs))
        return self

    def finish(self, status: Optional[str] = None,
               end: Optional[float] = None):
        """Complete the span and move it into the ring buffer. Safe to
        call once; a second call is ignored (exception paths)."""
        if self.end is not None:
            return
        self.end = self.tracer.now() if end is None else end
        if status is not None:
            self.status = status
        self.tracer._record(self)

    # -- context-manager protocol (stack-tracked spans) -------------------
    def __enter__(self) -> "Span":
        self.tracer._push(self)
        self._on_stack = True
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._on_stack:
            self.tracer._pop(self)
            self._on_stack = False
        self.finish(status="error" if exc_type is not None else None)
        return False

    def to_record(self) -> Dict[str, Any]:
        """JSONL record (schema checked by :func:`validate_trace_record`)."""
        tr = self.tracer
        rec: Dict[str, Any] = {
            "kind": "span",
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "ts": tr.to_wall(self.start),
            "dur_s": round(self.duration_s, 9),
            "thread": self.thread,
            "status": self.status,
        }
        if self.attrs:
            rec["attrs"] = _jsonable_dict(self.attrs)
        if self.events:
            rec["events"] = [
                {"ts": tr.to_wall(t), "name": n,
                 **({"attrs": _jsonable_dict(a)} if a else {})}
                for t, n, a in self.events]
        return rec


class _NoopSpan:
    """The disabled-mode span: a single shared instance whose every
    method is a no-op — ``tracer.span()`` while disabled allocates
    nothing (identity-tested in tests/test_tracing.py)."""

    __slots__ = ()
    trace_id = _NO_ID
    span_id = _NO_ID
    parent_id = _NO_ID
    name = ""
    status = "noop"
    events: List[tuple] = []
    attrs: Dict[str, Any] = {}
    duration_s = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attrs(self, **attrs):
        return self

    def add_event(self, name, **attrs):
        return self

    def finish(self, status=None, end=None):
        pass


NOOP_SPAN = _NoopSpan()


class _Stack(threading.local):
    def __init__(self):
        self.spans: List[Span] = []


class Tracer:
    """Span factory + bounded ring buffer + exporters.

    The clock is ``time.monotonic`` (matching the engine's step timers);
    :meth:`to_wall` maps it onto unix time via an anchor taken at
    construction so exported records carry real timestamps.
    """

    now = staticmethod(time.monotonic)

    def __init__(self, capacity: int = 4096, enabled: bool = True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.enabled = bool(enabled)
        self._buf: "deque[Span]" = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._stack = _Stack()
        self._span_ids = itertools.count(1)
        self._trace_ids = itertools.count(1)
        self._mono0 = time.monotonic()
        self._wall0 = time.time()
        self.dropped = 0            # spans evicted by the ring bound

    # -- lifecycle --------------------------------------------------------
    def enable(self, capacity: Optional[int] = None):
        if capacity is not None and capacity != self.capacity:
            if capacity < 1:
                raise ValueError(f"capacity must be >= 1, got {capacity}")
            with self._lock:
                self.capacity = capacity
                evicted = max(len(self._buf) - capacity, 0)
                self.dropped += evicted     # shrinking evicts oldest
                self._buf = deque(self._buf, maxlen=capacity)
        self.enabled = True
        return self

    def disable(self):
        self.enabled = False
        return self

    def clear(self):
        with self._lock:
            self._buf.clear()
            self.dropped = 0

    def to_wall(self, t: float) -> float:
        return self._wall0 + (t - self._mono0)

    # -- span creation ----------------------------------------------------
    def span(self, name: str, parent: Optional[Span] = None, **attrs):
        """Stack-tracked span context manager. Disabled → the shared
        no-op (zero allocation). Parent defaults to this thread's
        current span; a root span starts a new trace."""
        if not self.enabled:
            return NOOP_SPAN
        return self._make(name, parent, attrs)

    def start_span(self, name: str, parent: Optional[Span] = None,
                   trace_id: Optional[int] = None, **attrs):
        """Manual span — NOT pushed on the thread stack; the caller owns
        its lifetime and must ``finish()`` it (request-lifecycle roots
        that live across many engine steps, cross-thread children).
        ``trace_id`` adopts an externally minted trace id (a fleet
        router's, a remote caller's) instead of starting a fresh trace —
        the propagation hook that lets one timeline cross process
        boundaries where no parent ``Span`` object can travel."""
        if not self.enabled:
            return NOOP_SPAN
        return self._make(name, parent, attrs, trace_id=trace_id)

    def record_span(self, name: str, start: Optional[float] = None,
                    end: Optional[float] = None,
                    duration_s: Optional[float] = None,
                    parent: Optional[Span] = None,
                    status: Optional[str] = None,
                    trace_id: Optional[int] = None,
                    **attrs) -> Optional[Span]:
        """Record an already-measured interval as a completed span (the
        engine times its jitted calls anyway; this turns those stamps
        into timeline entries without a second clock read). Give either
        ``start``/``end`` in this tracer's clock, or ``duration_s``
        (ends now)."""
        if not self.enabled:
            return None
        if end is None:
            end = self.now()
        if start is None:
            start = end - (duration_s or 0.0)
        sp = self._make(name, parent, attrs, start=start,
                        trace_id=trace_id)
        sp.finish(status=status, end=end)
        return sp

    def _make(self, name, parent, attrs, start=None,
              trace_id=None) -> Span:
        if parent is None:
            st = self._stack.spans
            parent = st[-1] if st else None
        if parent is None or parent.span_id == _NO_ID:
            if trace_id is None:
                trace_id = next(self._trace_ids)
            parent_id = _NO_ID
        else:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        return Span(self, trace_id, next(self._span_ids), parent_id,
                    name, self.now() if start is None else start, attrs)

    def current(self) -> Optional[Span]:
        st = self._stack.spans
        return st[-1] if st else None

    def _push(self, span: Span):
        self._stack.spans.append(span)

    def _pop(self, span: Span):
        st = self._stack.spans
        if st and st[-1] is span:
            st.pop()
        elif span in st:            # exception-skewed exit order
            st.remove(span)

    def _record(self, span: Span):
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(span)

    # -- views ------------------------------------------------------------
    def spans(self, name: Optional[str] = None,
              trace_id: Optional[int] = None,
              limit: Optional[int] = None) -> List[Span]:
        """Snapshot of the ring (oldest → newest), optionally filtered."""
        with self._lock:
            out = list(self._buf)
        if name is not None:
            out = [s for s in out if s.name == name]
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        if limit is not None:
            out = out[-limit:] if limit > 0 else []
        return out

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-name span counts + total seconds (the report() table)."""
        agg: Dict[str, Dict[str, float]] = {}
        for s in self.spans():
            a = agg.setdefault(s.name, {"count": 0, "total_s": 0.0})
            a["count"] += 1
            a["total_s"] += s.duration_s
        return agg

    # -- exporters --------------------------------------------------------
    def export_jsonl(self, path: str) -> int:
        """Append every buffered span to a JSONL file, one flushed line
        per span (crash loses at most the partial final line — same
        contract as the metrics run log). Returns spans written."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        spans = self.spans()
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(
                {"kind": "trace_meta",
                 "schema_version": TRACE_SCHEMA_VERSION,
                 "ts": time.time(), "capacity": self.capacity,
                 "dropped": self.dropped}) + "\n")
            f.flush()
            for s in spans:
                f.write(json.dumps(s.to_record(), sort_keys=True,
                                   default=str) + "\n")
                f.flush()
        return len(spans)

    def to_chrome(self) -> Dict[str, Any]:
        """Chrome trace-event JSON (Perfetto / chrome://tracing): spans
        as complete ("X") events, span events as instant ("i") markers,
        pid/tid from the recording process/thread. The ``args`` carry
        trace/span ids so one request's lifecycle is clickable."""
        out = records_to_chrome(s.to_record() for s in self.spans())
        out["otherData"] = {"tracer_capacity": self.capacity,
                            "dropped": self.dropped}
        return out

    def export_chrome(self, path: str) -> str:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_chrome(), f, default=str)
        return path


def _jsonable_dict(d: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in d.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
        else:
            try:
                out[k] = float(v)     # numpy/device scalars
            except Exception:
                out[k] = str(v)
    return out


# -- trace JSONL schema validation (check_metrics_log --trace) -------------

_SPAN_REQUIRED = {
    "trace_id": (int,),
    "span_id": (int,),
    "parent_id": (int,),
    "name": (str,),
    "ts": (int, float),
    "dur_s": (int, float),
}


def validate_trace_record(rec: Dict[str, Any], *, index: int = 0):
    """Schema-check one trace JSONL record; raises ValueError with a
    precise message (the runlog validate_record discipline)."""

    def fail(msg):
        raise ValueError(f"trace record {index}: {msg} (record={rec!r})")

    if not isinstance(rec, dict):
        fail("not a JSON object")
    kind = rec.get("kind")
    if kind == "trace_meta":
        if not isinstance(rec.get("schema_version"), int):
            fail("trace_meta missing integer 'schema_version'")
        return
    if kind != "span":
        fail(f"unknown kind {kind!r} (expected 'span' or 'trace_meta')")
    for field, types in _SPAN_REQUIRED.items():
        v = rec.get(field)
        if not isinstance(v, types) or isinstance(v, bool):
            fail(f"missing/mistyped span field {field!r}")
    if rec["dur_s"] < 0:
        fail(f"negative dur_s: {rec['dur_s']}")
    if rec["span_id"] == rec["parent_id"]:
        fail("span is its own parent")
    for ev in rec.get("events", ()):
        if not isinstance(ev, dict) or not isinstance(ev.get("name"), str) \
                or not isinstance(ev.get("ts"), (int, float)):
            fail(f"malformed event {ev!r}")
    if rec["name"] == "router.handoff":
        # disaggregation contract (ISSUE 19): a handoff span rides the
        # REQUEST's trace id (one Perfetto timeline from route through
        # handoff to decode) and names its source; a successfully
        # placed handoff also names the decode destination
        attrs = rec.get("attrs") or {}
        if not attrs.get("src"):
            fail("router.handoff span missing 'src' attr")
        if rec["trace_id"] == 0:
            fail("router.handoff span is off the request's trace "
                 "(trace_id=0)")
        if rec.get("status", "ok") == "ok" and not attrs.get("dst"):
            fail("placed router.handoff span missing 'dst' attr")


def validate_trace_log(path: str, *, require_spans: int = 0) -> int:
    """Validate every record of a span JSONL export; returns the span
    count. A trailing partial line (crash artifact) is tolerated."""
    from paddle_tpu.observability import runlog
    spans = 0
    for i, rec in enumerate(runlog.read_run_log(path)):
        validate_trace_record(rec, index=i)
        if rec.get("kind") == "span":
            spans += 1
    if spans < require_spans:
        raise ValueError(
            f"{path}: {spans} span records < required {require_spans}")
    return spans


def chrome_trace_valid(trace: Dict[str, Any], *, require_events: int = 0):
    """Assert the Chrome trace-event invariants Perfetto needs: a
    ``traceEvents`` list whose every entry carries ``ph``/``ts``/
    ``pid``/``tid`` (and ``dur`` for complete events). Raises ValueError;
    used by run_ci's bench-artifact pin and the tests."""
    evs = trace.get("traceEvents")
    if not isinstance(evs, list):
        raise ValueError("chrome trace: missing traceEvents list")
    for i, e in enumerate(evs):
        for k in ("ph", "ts", "pid", "tid", "name"):
            if k not in e:
                raise ValueError(f"chrome trace event {i}: missing {k!r}")
        if e["ph"] == "X" and "dur" not in e:
            raise ValueError(f"chrome trace event {i}: X without dur")
    if len(evs) < require_events:
        raise ValueError(f"chrome trace: {len(evs)} events < required "
                         f"{require_events}")
    return len(evs)


def records_to_chrome(records: Iterable[Dict[str, Any]]
                      ) -> Dict[str, Any]:
    """Span JSONL records (``Span.to_record`` shape) → Chrome trace-
    event JSON. The ONE builder behind :meth:`Tracer.to_chrome` and
    :func:`chrome_trace_from_jsonl`, so the live and offline exports
    can never drift out of the :func:`chrome_trace_valid` contract."""
    pid = os.getpid()
    tids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    recs = list(records)
    base = min((r["ts"] for r in recs), default=0.0)
    for r in recs:
        tid = tids.setdefault(r.get("thread", "main"), len(tids))
        args = dict(r.get("attrs", {}), trace_id=r["trace_id"],
                    span_id=r["span_id"], parent_id=r["parent_id"],
                    status=r.get("status", "ok"))
        events.append({"name": r["name"], "cat": "span", "ph": "X",
                       "ts": (r["ts"] - base) * 1e6,
                       "dur": max(r["dur_s"], 0.0) * 1e6,
                       "pid": pid, "tid": tid, "args": args})
        for ev in r.get("events", ()):
            events.append({"name": ev["name"], "cat": "event", "ph": "i",
                           "s": "t", "ts": (ev["ts"] - base) * 1e6,
                           "pid": pid, "tid": tid,
                           "args": dict(ev.get("attrs", {}),
                                        trace_id=r["trace_id"],
                                        span_id=r["span_id"])})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def chrome_trace_from_jsonl(path: str, out_path: str) -> str:
    """Offline conversion: span JSONL export → Chrome trace file."""
    from paddle_tpu.observability import runlog
    recs = [r for r in runlog.read_run_log(path) if r.get("kind") == "span"]
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(records_to_chrome(recs), f)
    return out_path


# -- process-wide default tracer (disabled until someone enables it) -------

_DEFAULT = Tracer(enabled=False)


def default() -> Tracer:
    return _DEFAULT


def enable(capacity: Optional[int] = None) -> Tracer:
    """Turn on the process-wide tracer (serving binaries call this at
    startup; tests enable around the region they assert on)."""
    return _DEFAULT.enable(capacity)


def disable() -> Tracer:
    return _DEFAULT.disable()
