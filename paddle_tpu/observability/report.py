"""Unified run summary: one ``report()`` call renders everything the
registry saw — counters, gauges, histograms, and the profiler's
``record_event`` spans (which feed the same registry) — as one text
block. The reference's sorted profiler summary, generalized to the whole
telemetry surface.
"""

from __future__ import annotations

from typing import List, Optional

from paddle_tpu.observability import registry as _registry
from paddle_tpu.observability.registry import (Counter, Gauge, Histogram,
                                               _fmt_labels)

SPAN_METRIC = "record_event_span_seconds"


def report(reg: Optional[_registry.MetricsRegistry] = None) -> str:
    """Render the unified observability summary."""
    reg = reg or _registry.default()
    scalars: List[str] = []
    hists: List[str] = []
    spans: List[tuple] = []
    for m in reg.metrics():
        for key in m.labels_seen():
            labels = dict(key)
            if isinstance(m, Histogram):
                s = m.summary(**labels)
                if not s["count"]:
                    continue
                if m.name == SPAN_METRIC:
                    spans.append((labels.get("name", "?"), s))
                    continue
                hists.append(
                    f"{m.name}{_fmt_labels(key)}  count={s['count']} "
                    f"mean={s['mean']:.6g} min={s['min']:.6g} "
                    f"max={s['max']:.6g} sum={s['sum']:.6g}")
            else:
                kind = "c" if isinstance(m, Counter) else "g"
                scalars.append(f"{m.name}{_fmt_labels(key)} "
                               f"[{kind}] {m.value(**labels):.6g}")
    lines = ["== paddle_tpu observability report =="]
    if scalars:
        lines.append("-- counters / gauges --")
        lines.extend(sorted(scalars))
    if hists:
        lines.append("-- histograms --")
        lines.extend(sorted(hists))
    if spans:
        lines.append("-- record_event spans --")
        lines.append(f"{'Event':<32}{'Calls':>8}{'Total(s)':>12}"
                     f"{'Avg(ms)':>12}{'Max(ms)':>12}")
        for name, s in sorted(spans, key=lambda kv: -kv[1]["sum"]):
            lines.append(
                f"{name:<32}{s['count']:>8}{s['sum']:>12.4f}"
                f"{1e3 * s['mean']:>12.3f}{1e3 * s['max']:>12.3f}")
    if len(lines) == 1:
        lines.append("(no metrics recorded)")
    return "\n".join(lines)
