"""Unified run summary: one ``report()`` call renders everything the
registry saw — counters, gauges, histograms, the profiler's
``record_event`` spans (which feed the same registry), the tracer's
ring-buffer spans per subsystem, and the SLO burn-rate/alert state — as
one text block. The reference's sorted profiler summary, generalized to
the whole telemetry surface.
"""

from __future__ import annotations

from typing import List, Optional

from paddle_tpu.observability import registry as _registry
from paddle_tpu.observability import tracing as _tracing
from paddle_tpu.observability.registry import (Counter, Gauge, Histogram,
                                               _fmt_labels)

SPAN_METRIC = "record_event_span_seconds"


def report(reg: Optional[_registry.MetricsRegistry] = None,
           tracer: Optional[_tracing.Tracer] = None) -> str:
    """Render the unified observability summary."""
    reg = reg or _registry.default()
    tracer = tracer or _tracing.default()
    scalars: List[str] = []
    hists: List[str] = []
    spans: List[tuple] = []
    for m in reg.metrics():
        for key in m.labels_seen():
            labels = dict(key)
            if isinstance(m, Histogram):
                s = m.summary(**labels)
                if not s["count"]:
                    continue
                if m.name == SPAN_METRIC:
                    spans.append((labels.get("name", "?"), s))
                    continue
                hists.append(
                    f"{m.name}{_fmt_labels(key)}  count={s['count']} "
                    f"mean={s['mean']:.6g} min={s['min']:.6g} "
                    f"max={s['max']:.6g} sum={s['sum']:.6g}")
            else:
                kind = "c" if isinstance(m, Counter) else "g"
                scalars.append(f"{m.name}{_fmt_labels(key)} "
                               f"[{kind}] {m.value(**labels):.6g}")
    lines = ["== paddle_tpu observability report =="]
    if scalars:
        lines.append("-- counters / gauges --")
        lines.extend(sorted(scalars))
    if hists:
        lines.append("-- histograms --")
        lines.extend(sorted(hists))
    if spans:
        lines.append("-- record_event spans --")
        lines.append(f"{'Event':<32}{'Calls':>8}{'Total(s)':>12}"
                     f"{'Avg(ms)':>12}{'Max(ms)':>12}")
        for name, s in sorted(spans, key=lambda kv: -kv[1]["sum"]):
            lines.append(
                f"{name:<32}{s['count']:>8}{s['sum']:>12.4f}"
                f"{1e3 * s['mean']:>12.3f}{1e3 * s['max']:>12.3f}")
    trace_summary = tracer.summary()
    if trace_summary:
        lines.append("-- trace spans --")
        lines.append(f"{'Span':<32}{'Count':>8}{'Total(s)':>12}")
        for name, a in sorted(trace_summary.items(),
                              key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"{name:<32}{a['count']:>8.0f}"
                         f"{a['total_s']:>12.4f}")
        if tracer.dropped:
            lines.append(f"(ring dropped {tracer.dropped} older spans)")
    anatomy_lines = _anatomy_lines(reg)
    if anatomy_lines:
        lines.append("-- anatomy --")
        lines.extend(anatomy_lines)
    slo_lines = _slo_lines(reg)
    if slo_lines:
        lines.append("-- slo --")
        lines.extend(slo_lines)
    if len(lines) == 1:
        lines.append("(no metrics recorded)")
    return "\n".join(lines)


def _anatomy_lines(reg: _registry.MetricsRegistry) -> List[str]:
    """Step-anatomy digest, when a StepAnatomy fed this registry: the
    per-phase device-busy split, host-gap/host fractions, sampled
    collective-exposed time, and the resource-headroom snapshot."""
    out: List[str] = []
    phase_h = reg.get("anatomy_phase_seconds")
    if isinstance(phase_h, Histogram):
        sums = {}
        for key in phase_h.labels_seen():
            s = phase_h.summary(**dict(key))
            if s["count"]:
                sums[dict(key).get("phase", "?")] = s["sum"]
        busy = sum(sums.values())
        if busy > 0:
            split = " ".join(f"{p}={v / busy:.1%}"
                             for p, v in sorted(sums.items(),
                                                key=lambda kv: -kv[1]))
            out.append(f"phase_split {split} (busy={busy:.4g}s)")
    for gname, label in (("anatomy_host_gap_frac", "host_gap_frac"),
                         ("anatomy_host_frac", "host_frac"),
                         ("anatomy_collective_exposed_frac",
                          "collective_exposed_frac")):
        g = reg.get(gname)
        if isinstance(g, Gauge) and g.labels_seen():
            out.append(f"{label} {g.value():.4g}")
    coll = reg.get("anatomy_collective_exposed_seconds")
    if isinstance(coll, Histogram):
        s = coll.summary()
        if s["count"]:
            out.append(f"collective_exposed mean={s['mean']:.6g}s "
                       f"samples={s['count']}")
    head = reg.get("serving_headroom")
    if isinstance(head, Gauge):
        parts = []
        for key in sorted(head.labels_seen()):
            labels = dict(key)
            parts.append(f"{labels.get('resource', '?')}="
                         f"{head.value(**labels):.3g}")
        if parts:
            out.append("headroom " + " ".join(parts))
    return out


def _slo_lines(reg: _registry.MetricsRegistry) -> List[str]:
    """Current burn rates + alert counts, when SLO monitoring ran."""
    out: List[str] = []
    burn = reg.get("slo_burn_rate")
    if isinstance(burn, Gauge):
        for key in sorted(burn.labels_seen()):
            labels = dict(key)
            out.append(f"burn_rate slo={labels.get('slo', '?')} "
                       f"window={labels.get('window', '?')} "
                       f"{burn.value(**labels):.4g}")
    alerts = reg.get("slo_alerts_total")
    if isinstance(alerts, Counter):
        for key in sorted(alerts.labels_seen()):
            labels = dict(key)
            out.append(f"alerts slo={labels.get('slo', '?')} "
                       f"severity={labels.get('severity', '?')} "
                       f"{alerts.value(**labels):.0f}")
    return out
