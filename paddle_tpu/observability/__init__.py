"""Runtime telemetry subsystem: structured run metrics, recompilation /
step tracing, and cross-host aggregation.

Three parts (ISSUE 1 / TensorFlow-paper-style first-class telemetry):

1. **Metrics registry** (`registry.py`): process-wide named Counter /
   Gauge / Histogram with labels; Prometheus text exposition
   (:func:`render_prometheus`); flat :func:`snapshot` for logs.
2. **Run log + hot-path instrumentation** (`runlog.py`, `telemetry.py`,
   `recompile.py`): crash-safe JSONL (one record per step), the
   :class:`StepTelemetry` driver wired into ``Trainer.fit`` /
   ``Executor.train_from_dataset``, a :class:`RecompileDetector` over
   ``jax.monitoring`` compile events, and per-device memory gauges.
3. **Cross-host aggregation** (`aggregate.py`): :func:`aggregate`
   all-gathers scalars so host 0 sees min/max/mean per-host skew.

``profiler.record_event`` spans feed the same registry, so one
:func:`report` call dumps a unified summary.
"""

from paddle_tpu.observability.registry import (Counter, Gauge, Histogram,
                                               MetricsRegistry, counter,
                                               default, gauge, histogram)
from paddle_tpu.observability.runlog import (RunLogWriter, read_run_log,
                                             validate_record,
                                             validate_run_log)
from paddle_tpu.observability.recompile import (RecompileDetector,
                                                compile_count,
                                                install_compile_listener,
                                                shape_signature)
from paddle_tpu.observability.aggregate import aggregate, format_aggregate
from paddle_tpu.observability.telemetry import (StepTelemetry,
                                                device_memory_stats,
                                                record_memory_gauges)
from paddle_tpu.observability.report import SPAN_METRIC, report


def render_prometheus(reg: MetricsRegistry = None) -> str:
    """Prometheus text-format exposition of ``reg`` (default registry)."""
    return (reg or default()).render_prometheus()


def snapshot(reg: MetricsRegistry = None) -> dict:
    """Flat scalar snapshot of ``reg`` (default registry)."""
    return (reg or default()).snapshot()


_SPAN_NAME_CAP = 256


def observe_span(name: str, seconds: float,
                 reg: MetricsRegistry = None):
    """Feed one profiler ``record_event`` span into the registry (the
    unified-summary bridge; called by ``paddle_tpu.profiler``).

    Cardinality-bounded: record_event names can be dynamic (per-shard,
    per-request), and the registry keeps one series per name for the
    process lifetime — beyond ``_SPAN_NAME_CAP`` distinct names, new
    ones lump into the ``__other__`` series instead of growing memory
    without bound."""
    h = (reg or default()).histogram(
        SPAN_METRIC, "host record_event span durations")
    seen = h.labels_seen()
    if len(seen) >= _SPAN_NAME_CAP and (("name", str(name)),) not in seen:
        name = "__other__"
    h.observe(seconds, name=name)


__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "counter",
    "default", "gauge", "histogram", "RunLogWriter", "read_run_log",
    "validate_record", "validate_run_log", "RecompileDetector",
    "compile_count", "install_compile_listener", "shape_signature",
    "aggregate", "format_aggregate", "StepTelemetry",
    "device_memory_stats", "record_memory_gauges", "SPAN_METRIC",
    "report", "render_prometheus", "snapshot", "observe_span",
]
