"""Runtime telemetry subsystem: metrics, traces, exposition, anatomy.

Four pillars (ISSUE 1 + ISSUE 10 + ISSUE 16 / TensorFlow-paper-style
first-class telemetry):

1. **Metrics** (`registry.py`, `runlog.py`, `telemetry.py`,
   `recompile.py`, `aggregate.py`): process-wide named Counter / Gauge /
   Histogram with labels (thread-safe, with a lock-protected bound-child
   hot path); Prometheus text exposition; crash-safe JSONL run logs;
   the :class:`StepTelemetry` driver wired into ``Trainer.fit`` /
   ``Executor.train_from_dataset``; a :class:`RecompileDetector` over
   ``jax.monitoring`` compile events; cross-host min/mean/max skew.
2. **Traces** (`tracing.py`): request-lifecycle spans in a bounded ring
   buffer — thread-local span stacks, zero-cost-when-disabled no-op
   spans, JSONL + Chrome-trace (Perfetto) exporters — instrumenting the
   serving engines, scheduler decisions, Trainer steps, and snapshot
   save/restore. ``profiler.record_event`` regions fold into the same
   timeline.
3. **Live exposition + SLO monitoring** (`exposition.py`, `slo.py`):
   an opt-in stdlib HTTP endpoint serving ``/metrics`` / ``/healthz`` /
   ``/traces`` from a running process, and a multi-window burn-rate
   monitor over the latency histograms (``slo_burn_rate`` gauge,
   edge-triggered ``slo_alerts_total`` alerts into metrics AND trace).
4. **Step anatomy + crash flight recorder** (`anatomy.py`, `flight.py`):
   per-jitted-step wall-time decomposition (host gap, phase-split device
   busy, host assembly, sampled collective-exposed time via the
   ``tp_probe`` discipline) feeding histograms/gauges AND trace spans;
   a bounded :class:`FlightRecorder` black box per replica that dumps
   schema-validated postmortem bundles (anatomy JSONL + Chrome trace +
   health trajectory) on eject / breaker-open / shed spikes, served
   live at ``/debug/postmortem`` and rendered by ``tools/postmortem.py``.

One :func:`report` call dumps a unified summary across all four.
"""

from paddle_tpu.observability import anatomy, exposition, flight, slo, tracing
from paddle_tpu.observability.anatomy import (StepAnatomy,
                                              validate_anatomy_log,
                                              validate_anatomy_record,
                                              validate_anatomy_records)
from paddle_tpu.observability.flight import (POSTMORTEM_SCHEMA,
                                             FlightRecorder,
                                             validate_postmortem_bundle,
                                             validate_postmortem_file,
                                             write_bundle)
from paddle_tpu.observability.registry import (Counter, Gauge, Histogram,
                                               MetricsRegistry, counter,
                                               default, gauge, histogram)
from paddle_tpu.observability.runlog import (RunLogWriter, read_run_log,
                                             validate_record,
                                             validate_run_log)
from paddle_tpu.observability.recompile import (RecompileDetector,
                                                compile_count,
                                                install_compile_listener,
                                                shape_signature)
from paddle_tpu.observability.aggregate import aggregate, format_aggregate
from paddle_tpu.observability.telemetry import (StepTelemetry,
                                                device_memory_stats,
                                                record_memory_gauges)
from paddle_tpu.observability.report import SPAN_METRIC, report
from paddle_tpu.observability.tracing import (Span, Tracer,
                                              chrome_trace_valid,
                                              validate_trace_log)
from paddle_tpu.observability.exposition import ExpositionServer
from paddle_tpu.observability.slo import BurnRateMonitor


def render_prometheus(reg: MetricsRegistry = None) -> str:
    """Prometheus text-format exposition of ``reg`` (default registry)."""
    return (reg or default()).render_prometheus()


def snapshot(reg: MetricsRegistry = None) -> dict:
    """Flat scalar snapshot of ``reg`` (default registry)."""
    return (reg or default()).snapshot()


_SPAN_NAME_CAP = 256


def observe_span(name: str, seconds: float,
                 reg: MetricsRegistry = None):
    """Feed one profiler ``record_event`` span into the registry (the
    unified-summary bridge; called by ``paddle_tpu.profiler``).

    Cardinality-bounded: record_event names can be dynamic (per-shard,
    per-request), and the registry keeps one series per name for the
    process lifetime — beyond ``_SPAN_NAME_CAP`` distinct names, new
    ones lump into the ``__other__`` series instead of growing memory
    without bound."""
    h = (reg or default()).histogram(
        SPAN_METRIC, "host record_event span durations")
    seen = h.labels_seen()
    if len(seen) >= _SPAN_NAME_CAP and (("name", str(name)),) not in seen:
        name = "__other__"
    h.observe(seconds, name=name)


__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "counter",
    "default", "gauge", "histogram", "RunLogWriter", "read_run_log",
    "validate_record", "validate_run_log", "RecompileDetector",
    "compile_count", "install_compile_listener", "shape_signature",
    "aggregate", "format_aggregate", "StepTelemetry",
    "device_memory_stats", "record_memory_gauges", "SPAN_METRIC",
    "report", "render_prometheus", "snapshot", "observe_span",
    "Span", "Tracer", "validate_trace_log", "chrome_trace_valid",
    "ExpositionServer", "BurnRateMonitor",
    "StepAnatomy", "validate_anatomy_record", "validate_anatomy_records",
    "validate_anatomy_log", "FlightRecorder", "POSTMORTEM_SCHEMA",
    "validate_postmortem_bundle", "validate_postmortem_file",
    "write_bundle",
    "tracing", "exposition", "slo", "anatomy", "flight",
]
