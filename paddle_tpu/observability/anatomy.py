"""Step-time anatomy: where does one jitted serving step's wall time go.

The tracing ring (PR 8) shows request lifecycles; the registry shows
aggregate latencies. Neither answers the scheduling question ROADMAP
items 1/3/5 block on: per *step*, how much time is host gap between
device steps, how much is device busy split by phase (prefill / decode /
draft / verify), how much is host assembly, and how much of the busy
time is *collective-exposed* (the tp tax you could hide or shard away).

:class:`StepAnatomy` is the host-side accumulator the engine drives
around its fixed-shape calls — nothing here touches jitted code, so the
zero-steady-state-recompile invariant is untouched:

- ``begin_step()`` stamps the step start and the host gap since the
  previous step ended;
- ``add_phase(phase, start, end)`` records one timed device interval
  (the engine already holds these stamps around every jitted call —
  no extra clock reads on the hot path);
- ``set_collective(real_s, probe_s)`` lands a sampled collectives-
  elided probe measurement (the ``tp_probe`` discipline: same shapes,
  psum elided, delta = exposed collective time);
- ``end_step(tokens=...)`` closes the record, pushes it into a bounded
  ring, publishes registry histograms/gauges, and emits trace spans so
  one Perfetto export shows anatomy alongside ``serving.request``.

Records are plain dicts (JSONL-exportable, crash-safe via the runlog
discipline) validated by :func:`validate_anatomy_record` /
:func:`validate_anatomy_log` — the schema ``tools/check_metrics_log.py
--anatomy`` enforces: monotonic step ids, non-negative times, and phase
sums bounded by step wall time.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from paddle_tpu.observability import registry as _registry
from paddle_tpu.observability import tracing as _tracing

ANATOMY_SCHEMA_VERSION = 1

# the engine's phase vocabulary; validation accepts these plus any
# future phase name (schema checks types, not the closed set)
PHASES = ("prefill", "decode", "draft", "verify")

# phase-time floats compare against wall time measured by separate
# clock reads; allow this much skew before calling the record corrupt
_EPS = 1e-6


class StepAnatomy:
    """Per-step wall-time decomposition with a bounded record ring.

    Single-writer (the engine step thread); reads (``records()``,
    ``summary()``, the flight recorder's dump) are lock-protected so
    exposition/monitor threads can snapshot mid-step.
    """

    now = staticmethod(time.monotonic)

    def __init__(self, registry: Optional[_registry.MetricsRegistry] = None,
                 tracer: Optional[_tracing.Tracer] = None,
                 capacity: int = 2048):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.registry = registry or _registry.default()
        self.tracer = tracer or _tracing.default()
        self.capacity = capacity
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._cur: Optional[Dict[str, Any]] = None
        self._last_end: Optional[float] = None
        self._step_seq = 0
        self._mono0 = time.monotonic()
        self._wall0 = time.time()
        # totals for summary() — cheap running sums, not ring-derived,
        # so the summary reflects the whole run even after ring wrap
        self._tot = {"steps": 0, "wall_s": 0.0, "gap_s": 0.0,
                     "host_s": 0.0, "tokens": 0,
                     "probe_samples": 0, "collective_exposed_s": 0.0,
                     "probed_wall_s": 0.0}
        self._tot_phase: Dict[str, float] = {}
        r = self.registry
        self._h_wall = r.histogram(
            "anatomy_step_wall_seconds",
            "serving step wall time (begin_step..end_step)")
        self._h_gap = r.histogram(
            "anatomy_host_gap_seconds",
            "host gap between consecutive steps")
        self._h_phase = r.histogram(
            "anatomy_phase_seconds",
            "device-busy time per step by phase")
        self._h_coll = r.histogram(
            "anatomy_collective_exposed_seconds",
            "sampled exposed collective time per probed step")
        self._g_gap_frac = r.gauge(
            "anatomy_host_gap_frac",
            "fraction of timeline spent in host gaps between steps")
        self._g_host_frac = r.gauge(
            "anatomy_host_frac",
            "fraction of step wall spent in host assembly/data wait")
        self._g_coll_frac = r.gauge(
            "anatomy_collective_exposed_frac",
            "exposed collective time / wall on probed steps")
        self._c_steps = r.counter(
            "anatomy_steps_total", "anatomy records closed").child()
        self._c_probes = r.counter(
            "anatomy_probe_samples_total",
            "collective probe samples taken").child()
        self._phase_children: Dict[str, object] = {}

    def to_wall(self, t: float) -> float:
        return self._wall0 + (t - self._mono0)

    # -- step lifecycle ---------------------------------------------------
    def begin_step(self, step_id: Optional[int] = None) -> None:
        t0 = self.now()
        gap = (t0 - self._last_end) if self._last_end is not None else 0.0
        if step_id is None:
            step_id = self._step_seq
        self._step_seq = step_id + 1
        self._cur = {"step": int(step_id), "t0": t0,
                     "gap_s": max(gap, 0.0), "phases": {},
                     "intervals": [], "collective": None}

    def add_phase(self, phase: str, start: float, end: float) -> None:
        """Attribute one device interval (tracer-clock stamps the engine
        already took around the jitted call) to ``phase``."""
        cur = self._cur
        if cur is None:
            return
        dur = max(end - start, 0.0)
        cur["phases"][phase] = cur["phases"].get(phase, 0.0) + dur
        cur["intervals"].append((phase, start, end))

    def cancel_step(self) -> None:
        """Abandon the open step without recording it (an idle engine
        tick). The gap anchor still advances, so the next real step's
        host gap measures dispatch overhead, not queue-empty waiting."""
        if self._cur is not None:
            self._cur = None
            self._last_end = self.now()

    def set_collective(self, real_s: float, probe_s: float) -> None:
        """Land a sampled collectives-elided probe: ``real_s`` is the
        full spmd step, ``probe_s`` the same shapes with the psum
        elided; the positive delta is the exposed collective time."""
        cur = self._cur
        if cur is None:
            return
        cur["collective"] = (float(real_s), float(probe_s))

    def end_step(self, tokens: int = 0) -> Optional[Dict[str, Any]]:
        cur = self._cur
        if cur is None:
            return None
        self._cur = None
        t1 = self.now()
        wall = max(t1 - cur["t0"], 0.0)
        phases = {p: round(s, 9) for p, s in cur["phases"].items()}
        busy = sum(phases.values())
        host = max(wall - busy, 0.0)
        rec: Dict[str, Any] = {
            "kind": "anatomy",
            "schema_version": ANATOMY_SCHEMA_VERSION,
            "step": cur["step"],
            "ts": self.to_wall(cur["t0"]),
            "wall_s": round(wall, 9),
            "host_gap_s": round(cur["gap_s"], 9),
            "host_s": round(host, 9),
            "phases": phases,
            "tokens": int(tokens),
        }
        if cur["collective"] is not None:
            real_s, probe_s = cur["collective"]
            exposed = max(real_s - probe_s, 0.0)
            rec["probe_wall_s"] = round(probe_s, 9)
            rec["collective_exposed_s"] = round(exposed, 9)
        self._publish(rec, cur, t1)
        self._last_end = t1
        with self._lock:
            self._ring.append(rec)
        return rec

    def _publish(self, rec: Dict[str, Any], cur: Dict[str, Any],
                 t1: float) -> None:
        wall = rec["wall_s"]
        self._h_wall.observe(wall)
        self._h_gap.observe(rec["host_gap_s"])
        for phase, s in rec["phases"].items():
            ch = self._phase_children.get(phase)
            if ch is None:
                ch = self._phase_children[phase] = \
                    self._h_phase.child(phase=phase)
            ch.observe(s)
        self._c_steps.inc()
        t = self._tot
        t["steps"] += 1
        t["wall_s"] += wall
        t["gap_s"] += rec["host_gap_s"]
        t["host_s"] += rec["host_s"]
        t["tokens"] += rec["tokens"]
        for phase, s in rec["phases"].items():
            self._tot_phase[phase] = self._tot_phase.get(phase, 0.0) + s
        timeline = t["wall_s"] + t["gap_s"]
        if timeline > 0:
            self._g_gap_frac.set(t["gap_s"] / timeline)
        if t["wall_s"] > 0:
            self._g_host_frac.set(t["host_s"] / t["wall_s"])
        if "collective_exposed_s" in rec:
            self._c_probes.inc()
            self._h_coll.observe(rec["collective_exposed_s"])
            t["probe_samples"] += 1
            t["collective_exposed_s"] += rec["collective_exposed_s"]
            t["probed_wall_s"] += wall
            if t["probed_wall_s"] > 0:
                self._g_coll_frac.set(
                    t["collective_exposed_s"] / t["probed_wall_s"])
        tracer = self.tracer
        if tracer.enabled:
            attrs = {"step": rec["step"], "host_gap_s": rec["host_gap_s"],
                     "host_s": rec["host_s"], "tokens": rec["tokens"]}
            if "collective_exposed_s" in rec:
                attrs["collective_exposed_s"] = rec["collective_exposed_s"]
            sp = tracer.record_span("anatomy.step", start=cur["t0"],
                                    end=t1, **attrs)
            for phase, s0, s1 in cur["intervals"]:
                tracer.record_span(f"anatomy.{phase}", start=s0, end=s1,
                                   parent=sp, step=rec["step"])

    # -- views ------------------------------------------------------------
    def records(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Ring snapshot, oldest → newest."""
        with self._lock:
            out = list(self._ring)
        if limit is not None:
            out = out[-limit:] if limit > 0 else []
        return out

    def last(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def summary(self) -> Dict[str, Any]:
        """Whole-run aggregate (survives ring wrap): phase split,
        host-gap fraction, and the sampled collective economics."""
        t = dict(self._tot)
        steps = t["steps"]
        wall = t["wall_s"]
        timeline = wall + t["gap_s"]
        out: Dict[str, Any] = {
            "steps": steps,
            "wall_s": wall,
            "tokens": t["tokens"],
            "host_gap_frac": (t["gap_s"] / timeline) if timeline else 0.0,
            "host_frac": (t["host_s"] / wall) if wall else 0.0,
            "phase_s": dict(self._tot_phase),
            "phase_frac": {p: (s / wall if wall else 0.0)
                           for p, s in self._tot_phase.items()},
            "probe_samples": t["probe_samples"],
        }
        if t["probe_samples"]:
            out["collective_exposed_s"] = (
                t["collective_exposed_s"] / t["probe_samples"])
            out["collective_exposed_frac"] = (
                t["collective_exposed_s"] / t["probed_wall_s"]
                if t["probed_wall_s"] else 0.0)
        return out

    def export_jsonl(self, path: str) -> int:
        """Append the ring to a JSONL file (one flushed line per record
        — the runlog crash-safety contract). Returns records written."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        recs = self.records()
        with open(path, "a", encoding="utf-8") as f:
            for rec in recs:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
                f.flush()
        return len(recs)


# -- schema validation (check_metrics_log --anatomy) -----------------------

def validate_anatomy_record(rec: Dict[str, Any], *, index: int = 0,
                            prev_step: Optional[int] = None) -> int:
    """Schema-check one anatomy record; returns its step id so callers
    can thread the monotonicity check. Raises ValueError with a precise
    message (the runlog discipline)."""

    def fail(msg):
        raise ValueError(f"anatomy record {index}: {msg} (record={rec!r})")

    if not isinstance(rec, dict):
        fail("not a JSON object")
    if rec.get("kind") != "anatomy":
        fail(f"kind is {rec.get('kind')!r}, expected 'anatomy'")
    step = rec.get("step")
    if not isinstance(step, int) or isinstance(step, bool):
        fail("missing/mistyped integer 'step'")
    if prev_step is not None and step <= prev_step:
        fail(f"step ids not monotonic: {step} after {prev_step}")
    for field in ("wall_s", "host_gap_s", "host_s", "ts"):
        v = rec.get(field)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            fail(f"missing/mistyped numeric {field!r}")
        if field != "ts" and v < 0:
            fail(f"negative {field}: {v}")
    phases = rec.get("phases")
    if not isinstance(phases, dict):
        fail("missing 'phases' object")
    for p, s in phases.items():
        if not isinstance(p, str):
            fail(f"non-string phase key {p!r}")
        if not isinstance(s, (int, float)) or isinstance(s, bool) or s < 0:
            fail(f"phase {p!r} has bad duration {s!r}")
    if sum(phases.values()) > rec["wall_s"] + _EPS:
        fail(f"phase sum {sum(phases.values()):.9f} exceeds wall "
             f"{rec['wall_s']:.9f}")
    if "collective_exposed_s" in rec:
        v = rec["collective_exposed_s"]
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
            fail(f"bad collective_exposed_s {v!r}")
    tok = rec.get("tokens", 0)
    if not isinstance(tok, int) or isinstance(tok, bool) or tok < 0:
        fail(f"bad tokens {tok!r}")
    return step


def validate_anatomy_records(recs: Iterable[Dict[str, Any]]) -> int:
    """Validate an in-memory record sequence (monotonic step ids
    included); returns the record count."""
    prev: Optional[int] = None
    n = 0
    for i, rec in enumerate(recs):
        prev = validate_anatomy_record(rec, index=i, prev_step=prev)
        n += 1
    return n


def validate_anatomy_log(path: str, *, require_steps: int = 0) -> int:
    """Validate an anatomy JSONL export; returns the record count. A
    trailing partial line (crash artifact) is tolerated."""
    from paddle_tpu.observability import runlog
    prev: Optional[int] = None
    n = 0
    for i, rec in enumerate(runlog.read_run_log(path)):
        prev = validate_anatomy_record(rec, index=i, prev_step=prev)
        n += 1
    if n < require_steps:
        raise ValueError(
            f"{path}: {n} anatomy records < required {require_steps}")
    return n
