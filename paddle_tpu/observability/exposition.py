"""Live exposition endpoint: scrape a RUNNING engine instead of killing
it and reading JSONL.

A stdlib ``http.server`` on a background daemon thread (no new
dependencies — the container rule), serving three read-only views:

  ``/metrics``  Prometheus text exposition of the registry (0.0.4 —
                what ``render_prometheus`` already emits; an external
                Prometheus scrapes the same numbers bench.py dumps).
  ``/healthz``  structured JSON health: server status + uptime + one
                object per registered provider (the serving engine
                publishes slot occupancy, queue depth, page
                utilization, recompile count — see
                ``ServingEngine.health``; the fleet router adds a
                breaker section: per-replica circuit-breaker states,
                routable capacity, eject/redrive totals). A provider
                that raises — or reports ``{"degraded": true}``, as
                the fleet does while any breaker is open — marks the
                response degraded (HTTP 503) instead of crashing the
                endpoint.
  ``/traces``   recent ring-buffer spans as JSON (``?limit=N``,
                ``?trace_id=T``), newest last.
  ``/debug/postmortem``
                recent flight-recorder postmortem bundles from every
                registered provider (``?limit=N`` most recent,
                ``?replica=NAME`` one provider) — the crash artifacts
                the fleet router dumps on eject / breaker-open / shed
                spikes, schema ``paddle_tpu.postmortem-v1``.

Opt-in and port-0 by default: nothing binds unless a caller starts a
server, and tests grab an ephemeral port so parallel CI runs never
collide. The handler thread only *reads* registry/tracer state (both
are lock-protected), so scraping never blocks the serving hot path.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

from paddle_tpu.observability import registry as _registry
from paddle_tpu.observability import tracing as _tracing

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class ExpositionServer:
    """Background-thread HTTP exposition over a registry + tracer.

    ::

        srv = ExpositionServer(registry=reg, tracer=tr).start()
        srv.add_health("serving", engine.health)
        ... requests hit http://127.0.0.1:{srv.port}/metrics ...
        srv.stop()
    """

    def __init__(self, registry: Optional[_registry.MetricsRegistry] = None,
                 tracer: Optional[_tracing.Tracer] = None,
                 port: int = 0, host: str = "127.0.0.1"):
        self.registry = registry or _registry.default()
        self.tracer = tracer or _tracing.default()
        self._host = host
        self._want_port = int(port)
        self._health: Dict[str, Callable[[], dict]] = {}
        self._postmortem: Dict[str, Callable[[], list]] = {}
        self._json: Dict[str, Callable[[], object]] = {}
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._t0 = time.monotonic()

    def add_health(self, name: str,
                   provider: Callable[[], dict]) -> "ExpositionServer":
        """Register a named health provider (a zero-arg callable
        returning a JSON-able dict); its output nests under ``name`` in
        the ``/healthz`` body."""
        self._health[name] = provider
        return self

    def add_postmortem(self, name: str,
                       provider: Callable[[], list]) -> "ExpositionServer":
        """Register a postmortem-bundle provider (a zero-arg callable
        returning a list of bundle dicts, oldest → newest — e.g.
        ``FleetRouter.postmortems`` or ``FlightRecorder.bundles``);
        served under ``/debug/postmortem``."""
        self._postmortem[name] = provider
        return self

    def add_json(self, path: str,
                 provider: Callable[[], object]) -> "ExpositionServer":
        """Register an extra read-only JSON route (a zero-arg callable
        returning a JSON-able value) — how subsystems the exposition
        server does not know about (e.g. the network front door's
        ``/debug/netlog`` ledger) hang their debug views off the one
        operator endpoint. Reserved routes cannot be shadowed."""
        route = "/" + path.strip("/")
        if route in ("/metrics", "/healthz", "/traces",
                     "/debug/postmortem", "/"):
            raise ValueError(f"route {route!r} is reserved")
        self._json[route] = provider
        return self

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "ExpositionServer":
        if self._httpd is not None:
            return self
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):    # silence per-request stderr
                pass

            def do_GET(self):
                server._handle(self)

        self._httpd = ThreadingHTTPServer((self._host, self._want_port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="exposition",
            daemon=True)
        self._t0 = time.monotonic()
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("exposition server not started")
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def __enter__(self) -> "ExpositionServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- request handling -------------------------------------------------
    def _handle(self, h: BaseHTTPRequestHandler):
        try:
            parsed = urlparse(h.path)
            route = parsed.path.rstrip("/") or "/"
            if route == "/metrics":
                body = self.registry.render_prometheus().encode()
                self._reply(h, 200, PROMETHEUS_CONTENT_TYPE, body)
            elif route == "/healthz":
                status, payload = self.healthz()
                self._reply(h, 200 if status == "ok" else 503,
                            "application/json",
                            json.dumps(payload, default=str).encode())
            elif route == "/traces":
                q = parse_qs(parsed.query)
                try:
                    limit = int(q["limit"][0]) if "limit" in q else None
                    trace_id = (int(q["trace_id"][0])
                                if "trace_id" in q else None)
                except ValueError as e:
                    # caller input error, not a server fault: a scraper
                    # must not page on endpoint health over a typo
                    self._reply(h, 400, "text/plain",
                                f"bad query parameter: {e}".encode())
                    return
                payload = self.traces(limit=limit, trace_id=trace_id)
                self._reply(h, 200, "application/json",
                            json.dumps(payload, default=str).encode())
            elif route == "/debug/postmortem":
                q = parse_qs(parsed.query)
                try:
                    limit = int(q["limit"][0]) if "limit" in q else None
                except ValueError as e:
                    self._reply(h, 400, "text/plain",
                                f"bad query parameter: {e}".encode())
                    return
                replica = q["replica"][0] if "replica" in q else None
                payload = self.postmortems(limit=limit, replica=replica)
                self._reply(h, 200, "application/json",
                            json.dumps(payload, default=str).encode())
            elif route in self._json:
                # the healthz discipline: a sick provider is a 503
                # with the error in the body, never a dead endpoint
                try:
                    payload, code = self._json[route](), 200
                except Exception as e:
                    payload = {"error": f"{type(e).__name__}: {e}"}
                    code = 503
                self._reply(h, code, "application/json",
                            json.dumps(payload, default=str).encode())
            else:
                routes = " ".join(
                    ["/metrics", "/healthz", "/traces",
                     "/debug/postmortem"] + sorted(self._json))
                self._reply(h, 404, "text/plain",
                            f"paddle_tpu exposition: {routes}\n"
                            .encode())
        except BrokenPipeError:
            pass                     # scraper went away mid-reply
        except Exception as e:       # never take the endpoint down
            try:
                self._reply(h, 500, "text/plain",
                            f"exposition error: {e}".encode())
            except Exception:
                pass

    @staticmethod
    def _reply(h, code: int, ctype: str, body: bytes):
        h.send_response(code)
        h.send_header("Content-Type", ctype)
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)

    # -- payload builders (also callable without HTTP, for tests) ---------
    def healthz(self):
        """(status, payload): "ok" unless any provider raised OR
        reported itself degraded (``{"degraded": true}`` in its
        payload — e.g. the fleet router's breaker section while any
        circuit breaker is not closed), so load balancers see a sick
        fleet as HTTP 503 without the provider having to crash."""
        status = "ok"
        providers: Dict[str, dict] = {}
        for name, fn in self._health.items():
            try:
                providers[name] = fn()
                if isinstance(providers[name], dict) \
                        and providers[name].get("degraded"):
                    status = "degraded"
            except Exception as e:
                status = "degraded"
                providers[name] = {"error": f"{type(e).__name__}: {e}"}
        payload = {
            "status": status,
            "time": time.time(),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "tracing_enabled": bool(self.tracer.enabled),
            "providers": providers,
        }
        return status, payload

    def postmortems(self, limit: Optional[int] = None,
                    replica: Optional[str] = None) -> dict:
        """Recent postmortem bundles across providers, oldest → newest;
        a provider that raises reports an error entry instead of taking
        the endpoint down (the healthz discipline)."""
        bundles: list = []
        errors: Dict[str, str] = {}
        for name, fn in self._postmortem.items():
            if replica is not None and name != replica:
                continue
            try:
                bundles.extend(fn())
            except Exception as e:
                errors[name] = f"{type(e).__name__}: {e}"
        bundles.sort(key=lambda b: b.get("ts", 0.0))
        if limit is not None and limit >= 0:
            bundles = bundles[-limit:]
        payload = {"count": len(bundles), "bundles": bundles}
        if errors:
            payload["errors"] = errors
        return payload

    def traces(self, limit: Optional[int] = None,
               trace_id: Optional[int] = None) -> dict:
        spans = self.tracer.spans(trace_id=trace_id, limit=limit)
        return {
            "capacity": self.tracer.capacity,
            "dropped": self.tracer.dropped,
            "count": len(spans),
            "spans": [s.to_record() for s in spans],
        }
