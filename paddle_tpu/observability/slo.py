"""SLO burn-rate monitoring: see deadline pressure BEFORE requests fail.

The serving engine already histograms TTFT and queue-wait; this module
turns those aggregates into the standard multi-window burn-rate signal
(the SRE-workbook alerting recipe): the **burn rate** is the observed
violation fraction — requests whose latency blew the budget — divided
by the error budget the objective allows. Burn 1.0 = exactly consuming
the budget; burn 14 = the whole month's budget gone in ~2 days.

Two windows guard against both failure modes of threshold alerting: the
**fast** window catches a sudden cliff quickly, the **slow** window
keeps one latency spike from paging anyone — an alert needs BOTH
windows over the threshold. Alerts are edge-triggered (one count per
excursion, re-armed when the burn drops back under), published three
ways at once:

  - ``slo_burn_rate{slo,window}`` gauge (scrapeable via ``/metrics``),
  - ``slo_alerts_total{slo,severity}`` counter,
  - an ``slo.alert`` span event into the trace timeline, so the alert
    sits next to the exact requests that caused it in Perfetto.

The monitor is pull-based and host-side: ``check()`` reads cumulative
histogram state under the registry locks (no per-request work on the
hot path) — the serving engine calls it once per ``step()``.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.observability import registry as _registry
from paddle_tpu.observability import tracing as _tracing

# (severity, burn threshold) — highest first; the classic page/ticket
# split: page at 14.4x (a 30-day budget gone in 2 days), ticket at 6x
DEFAULT_THRESHOLDS = (("page", 14.4), ("ticket", 6.0))


class BurnRateMonitor:
    """Burn-rate watch over one latency histogram vs one budget.

    ``objective`` is the target success fraction (0.99 → 1% of requests
    may exceed ``budget_s`` before the error budget is gone).
    ``windows`` is (fast_s, slow_s). A fake ``clock`` makes the window
    arithmetic unit-testable without sleeping.
    """

    def __init__(self, metric: str = "serving_ttft_seconds",
                 budget_s: float = 1.0, *,
                 objective: float = 0.99,
                 windows: Tuple[float, float] = (60.0, 300.0),
                 thresholds: Sequence[Tuple[str, float]]
                 = DEFAULT_THRESHOLDS,
                 registry: Optional[_registry.MetricsRegistry] = None,
                 tracer: Optional[_tracing.Tracer] = None,
                 clock=time.monotonic):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), "
                             f"got {objective}")
        if budget_s <= 0:
            raise ValueError(f"budget_s must be > 0, got {budget_s}")
        fast, slow = windows
        if fast > slow:
            raise ValueError(f"fast window {fast} > slow window {slow}")
        self.metric = metric
        self.budget_s = float(budget_s)
        self.objective = float(objective)
        self.error_budget = 1.0 - self.objective
        self.windows = (float(fast), float(slow))
        self.thresholds = sorted(thresholds, key=lambda t: -t[1])
        self.reg = registry or _registry.default()
        self.tracer = tracer or _tracing.default()
        self._clock = clock
        # (t, total_count, over_budget_count) samples, pruned past the
        # slow window (+1 baseline). Appends are rate-limited to
        # fast_window/60 so the deque holds ~60 fast-window / ~300
        # slow-window samples no matter how often check() runs — the
        # engine calls it every step, and per-step cost/memory must not
        # scale with step rate
        self._samples: Deque[Tuple[float, float, float]] = deque()
        self._min_sample_interval = max(self.windows[0] / 60.0, 1e-3)
        self._active: set = set()    # severities currently firing
        self.alerts_total = 0
        self.burn: Dict[str, float] = {"fast": 0.0, "slow": 0.0}
        self._g = self.reg.gauge(
            "slo_burn_rate",
            "error-budget burn rate (violation frac / allowed frac)")
        self._c = self.reg.counter(
            "slo_alerts_total", "edge-triggered SLO burn-rate alerts")
        # t=0 baseline so the first window covers monitoring start
        self._samples.append((self._clock(), *self._read()))

    # -- histogram read ----------------------------------------------------
    def _read(self) -> Tuple[float, float]:
        """(total, over_budget) cumulative counts from the histogram; a
        metric that does not exist yet reads as no traffic. Violations
        use the CONSERVATIVE bucket count (``count_over``): samples in
        the budget's own bucket never page — put the budget on a bucket
        edge for exact accounting."""
        h = self.reg.get(self.metric)
        if not isinstance(h, _registry.Histogram):
            return 0.0, 0.0
        total = 0.0
        over = 0.0
        for key in h.labels_seen():
            # one lock acquisition per series: a concurrent writer can
            # never skew over vs total within a sample
            t, o = h.count_and_over(self.budget_s, **dict(key))
            total += t
            over += o
        return total, over

    # -- the periodic check ------------------------------------------------
    def check(self) -> Dict[str, float]:
        """Sample the histogram, recompute both windows' burn rates,
        update the gauges, and fire/re-arm alerts. Returns the burn
        dict (also kept on ``self.burn``)."""
        now = self._clock()
        total, over = self._read()
        # rate-limited history: burn below always uses the CURRENT
        # (total, over) against the sampled baselines, so skipping an
        # append never staleness the result — it only bounds the deque
        if now - self._samples[-1][0] >= self._min_sample_interval:
            self._samples.append((now, total, over))
        slow_w = self.windows[1]
        # prune: keep one sample at-or-before the slow window start as
        # that window's baseline
        while len(self._samples) >= 2 \
                and self._samples[1][0] <= now - slow_w:
            self._samples.popleft()
        for name, win in zip(("fast", "slow"), self.windows):
            self.burn[name] = self._window_burn(now, win, total, over)
            self._g.set(self.burn[name], slo=self.metric, window=name)
        self._update_alerts()
        return dict(self.burn)

    def _window_burn(self, now, win, total, over) -> float:
        base_t, base_total, base_over = self._samples[0]
        for s in self._samples:
            if s[0] <= now - win:
                base_t, base_total, base_over = s
            else:
                break
        d_total = total - base_total
        # clamp into [0, d_total]: the conservative "over" count is not
        # monotonic across count_and_over's exact/conservative regimes
        # (e.g. all-violating traffic reads exact until an in-budget
        # sample lowers cell.min), and a negative violation delta must
        # never publish a negative burn rate
        d_over = max(min(over - base_over, d_total), 0.0)
        if d_total <= 0:
            return 0.0
        return (d_over / d_total) / self.error_budget

    def _update_alerts(self):
        """One count per excursion: firing a severity also marks every
        LOWER severity active (they are the same excursion), so burn
        decaying from the page band through the ticket band does not
        mint a second alert — only a fresh excursion (full recovery
        first) or an escalation to a higher severity counts."""
        fast, slow = self.burn["fast"], self.burn["slow"]
        fired = None
        fired_thr = None
        for sev, thr in self.thresholds:
            if fast >= thr and slow >= thr:
                fired, fired_thr = sev, thr  # highest severity only
                break
        for sev, thr in self.thresholds:
            if fast < thr or slow < thr:
                self._active.discard(sev)    # re-arm on recovery
        if fired is not None and fired not in self._active:
            for sev, thr in self.thresholds:
                if thr <= fired_thr:
                    self._active.add(sev)
            self.alerts_total += 1
            self._c.inc(slo=self.metric, severity=fired)
            if self.tracer.enabled:
                self.tracer.record_span(
                    "slo.alert", duration_s=0.0, severity=fired,
                    slo=self.metric, budget_s=self.budget_s,
                    burn_fast=round(fast, 3), burn_slow=round(slow, 3))

    # -- views -------------------------------------------------------------
    def alerting(self) -> List[str]:
        return sorted(self._active)

    def status(self) -> Dict[str, object]:
        """One JSON-able dict for /healthz and report()."""
        return {
            "slo": self.metric,
            "budget_s": self.budget_s,
            "objective": self.objective,
            "burn_fast": round(self.burn["fast"], 4),
            "burn_slow": round(self.burn["slow"], 4),
            "alerting": self.alerting(),
            "alerts_total": self.alerts_total,
        }
