"""Process-wide metrics registry: Counter / Gauge / Histogram with labels.

Reference mapping: the reference framework's runtime stats are scattered
(profiler summaries, per-pass VLOG counters, pserver-side monitor tables);
TensorFlow's system paper treats run metrics as a first-class subsystem.
Here ONE registry backs every consumer: the hot-path instrumentation
(trainer/executor/inference), the JSONL run log (runlog.py), Prometheus
text exposition for external scrapers, and ``observability.report()``.

Design: plain host-side Python (no jax imports — safe to use inside data
threads and before backend init), a single lock per registry, and label
sets keyed by sorted ``(key, value)`` tuples so ``counter.inc(host=0)``
and ``counter.inc(host=1)`` are independent series of one metric.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from paddle_tpu.analysis.concurrency import guarded_by

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape_label(v: str) -> str:
    # Prometheus exposition: backslash, double-quote and newline must be
    # escaped inside label values or the whole dump is unparseable
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt_labels(key: LabelKey) -> str:
    if not key:
        return ""
    return ("{" + ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
            + "}")


@guarded_by("_lock", "_series")
class _Metric:
    """Shared series bookkeeping; subclasses define the per-series cell.

    Thread-safety contract (audited under concurrent writers — serving
    step thread vs streaming applier vs snapshot writer): every
    label-map mutation AND every cell read/write happens under
    ``self._lock``; :meth:`child` is the lock-protected child-creation
    path that binds a label set once so hot-path updates skip the
    per-call label-key sort and double lock acquisition."""

    kind = "untyped"
    _child_cls: type = None

    def __init__(self, name: str, help: str = ""):
        if not name or any(c in name for c in " \t\n{}\","):
            raise ValueError(f"bad metric name {name!r}")
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: Dict[LabelKey, object] = {}

    def _cell(self, labels: Dict[str, object]):
        key = _label_key(labels)
        with self._lock:
            cell = self._series.get(key)
            if cell is None:
                cell = self._series[key] = self._new_cell()
            return cell

    def child(self, **labels):
        """Bind one label set to a reusable handle (prometheus-client
        ``labels()`` convention): cell creation is lock-protected here,
        and the handle's updates are a single lock acquisition with no
        label-key sorting — the hot-path form for per-step metrics."""
        return self._child_cls(self, self._cell(labels))

    def labels_seen(self) -> List[LabelKey]:
        with self._lock:
            return list(self._series)

    def remove(self, **labels) -> bool:
        """Drop one label set's series (tombstone). A scrape after this
        no longer exports the series at all — the contract FleetMonitor
        relies on when a replica is ejected: its per-replica gauges must
        disappear, not freeze at their last value forever. Returns
        whether a series was actually removed. Any ``child()`` handle
        bound to the removed cell keeps working but writes to a
        disconnected cell no exposition path reads."""
        key = _label_key(labels)
        with self._lock:
            return self._series.pop(key, None) is not None

    def remove_matching(self, **labels) -> int:
        """Drop every series whose label set includes all the given
        pairs (e.g. ``remove_matching(replica="r1")`` across metrics
        that also carry other labels). Returns series removed."""
        want = set(_label_key(labels))
        with self._lock:
            gone = [k for k in self._series if want <= set(k)]
            for k in gone:
                del self._series[k]
            return len(gone)


class _BoundChild:
    """A (metric, cell) pair: pre-resolved series handle."""

    __slots__ = ("_metric", "_cell")

    def __init__(self, metric: _Metric, cell):
        self._metric = metric
        self._cell = cell


class _CounterChild(_BoundChild):
    def inc(self, n: float = 1.0):
        if n < 0:
            raise ValueError(
                f"counter {self._metric.name} cannot decrease (n={n})")
        with self._metric._lock:
            self._cell[0] += n

    def value(self) -> float:
        with self._metric._lock:
            return self._cell[0]


class Counter(_Metric):
    """Monotonically increasing count (reference: per-op run counters)."""

    kind = "counter"
    _child_cls = _CounterChild

    def _new_cell(self):
        return [0.0]

    def inc(self, n: float = 1.0, **labels) -> "Counter":
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        cell = self._cell(labels)
        with self._lock:
            cell[0] += n
        return self

    def value(self, **labels) -> float:
        cell = self._cell(labels)
        with self._lock:
            return cell[0]


class _GaugeChild(_BoundChild):
    def set(self, v: float):
        with self._metric._lock:
            self._cell[0] = float(v)

    def inc(self, n: float = 1.0):
        with self._metric._lock:
            self._cell[0] += n

    def value(self) -> float:
        with self._metric._lock:
            return self._cell[0]


class Gauge(_Metric):
    """Point-in-time value (memory bytes, queue depth, worker id)."""

    kind = "gauge"
    _child_cls = _GaugeChild

    def _new_cell(self):
        return [0.0]

    def set(self, v: float, **labels) -> "Gauge":
        cell = self._cell(labels)
        with self._lock:
            cell[0] = float(v)
        return self

    def inc(self, n: float = 1.0, **labels) -> "Gauge":
        cell = self._cell(labels)
        with self._lock:
            cell[0] += n
        return self

    def value(self, **labels) -> float:
        cell = self._cell(labels)
        with self._lock:
            return cell[0]


# default buckets suit step/span latencies (seconds): 100us .. 100s
DEFAULT_BUCKETS = (1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5,
                   1.0, 5.0, 10.0, 50.0, 100.0)


class _HistCell:
    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self, nbuckets: int):
        self.counts = [0] * (nbuckets + 1)  # +1 = +Inf bucket
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf


class _HistogramChild(_BoundChild):
    def observe(self, v: float):
        self._metric._observe_cell(self._cell, float(v))


class Histogram(_Metric):
    """Cumulative-bucket histogram + running min/max/sum/count.

    min/max are not Prometheus-native but back ``aggregate()``'s cross-
    host skew view and the report() table."""

    kind = "histogram"
    _child_cls = _HistogramChild

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(sorted(buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")

    def _new_cell(self):
        return _HistCell(len(self.buckets))

    def observe(self, v: float, **labels) -> "Histogram":
        self._observe_cell(self._cell(labels), float(v))
        return self

    def _observe_cell(self, cell: _HistCell, v: float):
        with self._lock:
            i = 0
            for i, b in enumerate(self.buckets):
                if v <= b:
                    break
            else:
                i = len(self.buckets)
            cell.counts[i] += 1
            cell.count += 1
            cell.sum += v
            cell.min = min(cell.min, v)
            cell.max = max(cell.max, v)

    def summary(self, **labels) -> Dict[str, float]:
        cell = self._cell(labels)
        with self._lock:
            if not cell.count:
                return {"count": 0, "sum": 0.0, "mean": 0.0,
                        "min": 0.0, "max": 0.0}
            return {"count": cell.count, "sum": cell.sum,
                    "mean": cell.sum / cell.count,
                    "min": cell.min, "max": cell.max}

    def quantile(self, q: float, **labels) -> float:
        """Bucket-interpolated quantile estimate (the
        ``histogram_quantile`` convention): linear interpolation inside
        the bucket holding the q-th sample, clamped to the observed
        [min, max] so a wide bucket cannot report a value no sample ever
        reached. ``q`` in [0, 1]. Returns 0.0 for an empty histogram."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        cell = self._cell(labels)
        with self._lock:
            if not cell.count:
                return 0.0
            target = q * cell.count
            cum = 0.0
            lo = cell.min
            for i, c in enumerate(cell.counts):
                hi = (self.buckets[i] if i < len(self.buckets)
                      else cell.max)
                if c and cum + c >= target:
                    frac = (target - cum) / c
                    v = lo + frac * max(hi - lo, 0.0)
                    return min(max(v, cell.min), cell.max)
                cum += c
                # advance past EMPTY buckets too: the target bucket's
                # lower edge is its true floor, and a stale `lo` from
                # a distant outlier would interpolate below it
                lo = hi
            return cell.max

    def percentiles(self, *qs: float, **labels) -> Dict[str, float]:
        """{'p50': ..., 'p99': ...} for the given quantiles (0-1)."""
        return {f"p{q * 100:g}": self.quantile(q, **labels) for q in qs}

    def count_and_over(self, v: float, **labels):
        """(total, definitely-over-``v``) in ONE lock acquisition — the
        SLO monitor's atomic read: separate total/over reads could let a
        concurrent observe land between them and mint a phantom
        violation. "Over" is conservative: only buckets whose entire
        range lies above ``v`` count (samples sharing ``v``'s own bucket
        are treated as within budget) — a mid-bucket budget must never
        page on traffic that actually met it; put budgets on bucket
        edges for exact accounting."""
        v = float(v)
        cell = self._cell(labels)
        with self._lock:
            total = float(cell.count)
            if not cell.count or v >= cell.max:
                return total, 0.0
            if v < cell.min:
                return total, total
            over = 0.0
            lo = -math.inf
            for i, c in enumerate(cell.counts):
                if lo >= v:
                    over += c
                lo = (self.buckets[i] if i < len(self.buckets)
                      else math.inf)
            return total, over

    def count_over(self, v: float, **labels) -> float:
        """Number of observations definitely > ``v`` (see
        :meth:`count_and_over` for the semantics and the atomic pair)."""
        return self.count_and_over(v, **labels)[1]

    def count_le(self, v: float, **labels) -> float:
        """Estimated number of observations <= ``v`` (the inverse of
        :meth:`quantile`, same bucket interpolation; see
        :meth:`count_over` for the conservative SLO-side count)."""
        v = float(v)
        cell = self._cell(labels)
        with self._lock:
            if not cell.count:
                return 0.0
            if v >= cell.max:
                return float(cell.count)
            if v < cell.min:
                return 0.0
            cum = 0.0
            lo = cell.min
            for i, c in enumerate(cell.counts):
                hi = (self.buckets[i] if i < len(self.buckets)
                      else cell.max)
                if v <= hi:
                    if c and hi > lo:
                        frac = max(min((v - lo) / (hi - lo), 1.0), 0.0)
                        return cum + frac * c
                    return cum + (c if v >= hi else 0.0)
                cum += c
                lo = hi
            return float(cell.count)

    def _render_cell(self, labels: Dict[str, object]):
        """Consistent (counts, count, sum) snapshot for exposition —
        taken under the metric lock, so a concurrent ``observe`` can
        never produce a render whose bucket total disagrees with its
        ``_count`` line (the torn read the thread-safety audit found)."""
        cell = self._cell(labels)
        with self._lock:
            return list(cell.counts), cell.count, cell.sum


@guarded_by("_lock", "_metrics")
class MetricsRegistry:
    """Name -> metric table; the process-wide instance is ``default()``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def unregister(self, name: str):
        with self._lock:
            self._metrics.pop(name, None)

    # -- views -------------------------------------------------------------
    def snapshot(self) -> Dict[str, float]:
        """Flat {'name{label="v"}': scalar} view — what the JSONL log and
        aggregate() consume. Histograms flatten to _count/_sum/_min/_max/
        _mean suffixes."""
        out: Dict[str, float] = {}
        for m in self.metrics():
            for key in m.labels_seen():
                lab = _fmt_labels(key)
                if isinstance(m, Histogram):
                    s = m.summary(**dict(key))
                    for suffix in ("count", "sum", "mean", "min", "max"):
                        out[f"{m.name}_{suffix}{lab}"] = s[suffix]
                else:
                    out[f"{m.name}{lab}"] = m.value(**dict(key))
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (0.0.4): the contract that
        lets bench.py and an external scraper read the same numbers."""
        lines: List[str] = []
        for m in self.metrics():
            keys = m.labels_seen()
            if not keys:
                continue
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key in keys:
                labels = dict(key)
                if isinstance(m, Histogram):
                    counts, count, total = m._render_cell(labels)
                    cum = 0
                    for b, c in zip(m.buckets, counts):
                        cum += c
                        lab = _fmt_labels(key + (("le", _fmt_le(b)),))
                        lines.append(f"{m.name}_bucket{lab} {cum}")
                    cum += counts[-1]
                    lab = _fmt_labels(key + (("le", "+Inf"),))
                    lines.append(f"{m.name}_bucket{lab} {cum}")
                    lab = _fmt_labels(key)
                    lines.append(f"{m.name}_sum{lab} {_fmt_num(total)}")
                    lines.append(f"{m.name}_count{lab} {count}")
                else:
                    lab = _fmt_labels(key)
                    lines.append(
                        f"{m.name}{lab} {_fmt_num(m.value(**labels))}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt_le(b: float) -> str:
    return repr(float(b))


def _fmt_num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


_DEFAULT = MetricsRegistry()


def default() -> MetricsRegistry:
    return _DEFAULT


def counter(name: str, help: str = "") -> Counter:
    return _DEFAULT.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _DEFAULT.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return _DEFAULT.histogram(name, help, buckets=buckets)
