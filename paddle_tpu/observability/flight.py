"""Crash flight recorder: bounded black-box ring + postmortem bundles.

When a replica is ejected, a breaker opens, or shed spikes, the router
needs more than a counter increment — it needs the last N steps of
anatomy, the health trajectory, and the trace timeline of the victim
requests, captured *before* the evidence is garbage-collected with the
replica. :class:`FlightRecorder` is that black box: it rides along with
an engine (one per replica), keeps a bounded ring of recent health
snapshots next to the :class:`~paddle_tpu.observability.anatomy.
StepAnatomy` record ring, and on demand dumps a single self-contained,
schema-validated postmortem bundle:

- ``anatomy``: the recent per-step anatomy records (JSONL-shaped);
- ``health``: the replica's last health snapshot (+ the bounded
  trajectory in ``snapshots``);
- ``metrics``: a flat registry snapshot at dump time;
- ``chrome_trace``: the tracer ring rendered as Chrome trace-event
  JSON (Perfetto-loadable), so victim ``trace_ids`` are clickable;
- ``reason`` / ``replica`` / ``ts``: why, who, when.

Bundles validate via :func:`validate_postmortem_bundle` (run by
``tools/check_metrics_log.py --postmortem`` and the chaos bench leg)
and render offline via ``tools/postmortem.py``. Everything is host-side
and bounded: a month-long serving process keeps the most recent window,
and a dump on a *dead* replica still works — the rings outlive the
device state that crashed.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

from paddle_tpu.observability import registry as _registry
from paddle_tpu.observability import tracing as _tracing
from paddle_tpu.observability.anatomy import (StepAnatomy,
                                              validate_anatomy_records)

POSTMORTEM_SCHEMA = "paddle_tpu.postmortem-v1"

# a replica keeps the last few bundles it dumped so /debug/postmortem
# can serve them after the fact without unbounded growth
MAX_BUNDLES_KEPT = 8


class FlightRecorder:
    """Bounded black box for one replica/engine.

    ``note(health)`` appends a health snapshot (the engine calls it from
    its health refresh — cheap dict copy, every ``snapshot_every``-th
    call lands); ``dump(reason, ...)`` assembles the postmortem bundle.
    Thread-safe: the router dumps from its own thread while the engine
    step thread keeps noting.
    """

    def __init__(self, name: str = "replica",
                 anatomy: Optional[StepAnatomy] = None,
                 registry: Optional[_registry.MetricsRegistry] = None,
                 tracer: Optional[_tracing.Tracer] = None,
                 capacity: int = 256, snapshot_every: int = 8,
                 anatomy_tail: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1, got {snapshot_every}")
        self.name = name
        self.anatomy = anatomy
        self.registry = registry or _registry.default()
        self.tracer = tracer or _tracing.default()
        self.snapshot_every = snapshot_every
        self.anatomy_tail = anatomy_tail
        self._snaps: "deque[Dict[str, Any]]" = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._notes = 0
        self._bundles: "deque[Dict[str, Any]]" = deque(
            maxlen=MAX_BUNDLES_KEPT)
        self._c_dumps = self.registry.counter(
            "flight_postmortems_total",
            "postmortem bundles dumped, by reason")

    # -- black-box feed ---------------------------------------------------
    def note(self, health: Dict[str, Any]) -> None:
        """Record a health snapshot; only every ``snapshot_every``-th
        call lands in the ring (the engine notes once per step)."""
        with self._lock:
            self._notes += 1
            if (self._notes - 1) % self.snapshot_every:
                return
            self._snaps.append({"ts": time.time(), "health": dict(health)})

    def snapshots(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._snaps)

    # -- postmortem -------------------------------------------------------
    def dump(self, reason: str, trace_ids: Iterable[int] = (),
             health: Optional[Dict[str, Any]] = None,
             extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Assemble a postmortem bundle NOW. Safe on a dead replica:
        everything read here is host-side ring state."""
        snaps = self.snapshots()
        if health is None:
            health = snaps[-1]["health"] if snaps else {}
        anatomy_recs: List[Dict[str, Any]] = []
        anatomy_summary: Dict[str, Any] = {}
        if self.anatomy is not None:
            anatomy_recs = self.anatomy.records(limit=self.anatomy_tail)
            anatomy_summary = self.anatomy.summary()
        try:
            chrome = _tracing.records_to_chrome(
                s.to_record() for s in self.tracer.spans())
        except Exception:                     # never let telemetry break
            chrome = {"traceEvents": []}      # the dump path
        bundle: Dict[str, Any] = {
            "schema": POSTMORTEM_SCHEMA,
            "reason": str(reason),
            "replica": self.name,
            "ts": time.time(),
            "health": dict(health),
            "snapshots": snaps,
            "anatomy": anatomy_recs,
            "anatomy_summary": anatomy_summary,
            "metrics": self.registry.snapshot(),
            "trace_ids": sorted({int(t) for t in trace_ids}),
            "chrome_trace": chrome,
        }
        if extra:
            bundle["extra"] = dict(extra)
        self._c_dumps.inc(reason=str(reason))
        with self._lock:
            self._bundles.append(bundle)
        return bundle

    def bundles(self) -> List[Dict[str, Any]]:
        """Recently dumped bundles, oldest → newest (bounded)."""
        with self._lock:
            return list(self._bundles)


# -- bundle IO + schema validation ----------------------------------------

def write_bundle(bundle: Dict[str, Any], path: str) -> str:
    """Write one bundle as a self-contained JSON artifact."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(bundle, f, sort_keys=True, default=str)
    return path


def read_bundle(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def validate_postmortem_bundle(bundle: Dict[str, Any]) -> None:
    """Assert the postmortem bundle schema; raises ValueError with a
    precise message (same contract as the runlog/trace validators)."""

    def fail(msg):
        raise ValueError(f"postmortem bundle: {msg}")

    if not isinstance(bundle, dict):
        fail(f"is {type(bundle).__name__}, not an object")
    if bundle.get("schema") != POSTMORTEM_SCHEMA:
        fail(f"schema is {bundle.get('schema')!r}, "
             f"expected {POSTMORTEM_SCHEMA!r}")
    for field, types in (("reason", (str,)), ("replica", (str,)),
                         ("ts", (int, float)), ("health", (dict,)),
                         ("snapshots", (list,)), ("anatomy", (list,)),
                         ("metrics", (dict,)), ("trace_ids", (list,)),
                         ("chrome_trace", (dict,))):
        v = bundle.get(field)
        if not isinstance(v, types) or isinstance(v, bool):
            fail(f"missing/mistyped {field!r} "
                 f"({type(v).__name__}, want {types})")
    if not bundle["reason"]:
        fail("empty reason")
    for i, t in enumerate(bundle["trace_ids"]):
        if not isinstance(t, int) or isinstance(t, bool) or t < 0:
            fail(f"trace_ids[{i}] is {t!r}, want non-negative int")
    for i, snap in enumerate(bundle["snapshots"]):
        if not isinstance(snap, dict) or "ts" not in snap \
                or not isinstance(snap.get("health"), dict):
            fail(f"snapshots[{i}] malformed: {snap!r}")
    for k, v in bundle["metrics"].items():
        if not isinstance(k, str) \
                or not isinstance(v, (int, float)) or isinstance(v, bool):
            fail(f"metrics[{k!r}] is {v!r}, want numeric scalar")
    try:
        validate_anatomy_records(bundle["anatomy"])
    except ValueError as e:
        fail(f"anatomy section invalid: {e}")
    try:
        _tracing.chrome_trace_valid(bundle["chrome_trace"])
    except ValueError as e:
        fail(f"chrome_trace invalid: {e}")


def validate_postmortem_file(path: str) -> Dict[str, Any]:
    """Load + validate a bundle artifact; returns the bundle."""
    bundle = read_bundle(path)
    validate_postmortem_bundle(bundle)
    return bundle
