"""Structured JSONL run log: one record per step, crash-safe append.

The schema is the contract between the hot-path writers (Trainer.fit,
bench.py) and the consumers (tools/check_metrics_log.py, external
analysis, the BENCH_* trajectory): newline-delimited JSON, each line a
self-contained record. A crash mid-write loses at most the final
(partial) line — ``read_run_log`` tolerates and drops it.

Record kinds:
  run_meta   once at open: schema version, argv-ish context    (optional)
  step       per training step: timing/throughput/recompiles   (the bulk)
  summary    once at close: aggregate numbers                  (optional)

Step records carry (validated by :func:`validate_record`):
  ts                float  unix seconds
  kind              "step"
  step              int    global step index (>= 0)
  step_time_s       float  wall seconds for the step           (>= 0)
  examples_per_sec  float                                      (>= 0)
and optionally: epoch, tokens_per_sec, data_wait_s, compute_s,
recompiles (cumulative int), compiles_cum, metrics (dict of floats),
memory (per-device dict), host (process index).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional

SCHEMA_VERSION = 1

_KINDS = ("run_meta", "step", "summary")

# field -> (type(s), required) for step records
_STEP_REQUIRED = {
    "ts": (int, float),
    "step": (int,),
    "step_time_s": (int, float),
    "examples_per_sec": (int, float),
}
_STEP_NUMERIC_OPT = ("tokens_per_sec", "data_wait_s", "compute_s",
                     "recompiles", "compiles_cum", "epoch", "host")


class RunLogWriter:
    """Append-only JSONL writer. Every ``write`` flushes the line to the
    OS so a crashed run keeps everything up to its last whole step;
    ``fsync_every`` additionally fsyncs every N records (0 = never) for
    power-loss durability without per-step fsync cost."""

    def __init__(self, path: str, *, meta: Optional[Dict[str, Any]] = None,
                 fsync_every: int = 0):
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self.path = path
        self._f = open(path, "a", encoding="utf-8")
        self._fsync_every = int(fsync_every)
        self._since_sync = 0
        if meta is not None:
            self.write(dict(meta, kind="run_meta",
                            schema_version=SCHEMA_VERSION))

    def write(self, record: Dict[str, Any]):
        rec = dict(record)
        rec.setdefault("kind", "step")
        rec.setdefault("ts", time.time())
        line = json.dumps(rec, separators=(",", ":"), sort_keys=True,
                          default=_jsonable)
        self._f.write(line + "\n")
        self._f.flush()
        if self._fsync_every:
            self._since_sync += 1
            if self._since_sync >= self._fsync_every:
                os.fsync(self._f.fileno())
                self._since_sync = 0
        return rec

    def close(self):
        if not self._f.closed:
            self._f.flush()
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _jsonable(x):
    """Last-resort coercion: device scalars / numpy types -> python."""
    try:
        return float(x)
    except Exception:
        return str(x)


def read_run_log(path: str) -> List[Dict[str, Any]]:
    """Read all whole records; a trailing partial line (crash artifact)
    is dropped, an interior malformed line raises."""
    out: List[Dict[str, Any]] = []
    with open(path, encoding="utf-8") as f:
        lines = f.read().split("\n")
    # trailing "" after a final newline, or a partial record, is the tail
    body, tail = lines[:-1], lines[-1]
    for i, line in enumerate(body):
        if not line.strip():
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}:{i + 1}: malformed record: {e}") from e
    if tail.strip():
        try:
            out.append(json.loads(tail))
        except json.JSONDecodeError:
            pass  # partial final line: crash-safe read drops it
    return out


def validate_record(rec: Dict[str, Any], *, index: int = 0):
    """Schema-check one record; raises ValueError with a precise message.
    Shared by tools/check_metrics_log.py and the bench scripts."""

    def fail(msg):
        raise ValueError(f"record {index}: {msg} (record={rec!r})")

    if not isinstance(rec, dict):
        fail("not a JSON object")
    kind = rec.get("kind", "step")
    if kind not in _KINDS:
        fail(f"unknown kind {kind!r} (expected one of {_KINDS})")
    if not isinstance(rec.get("ts"), (int, float)):
        fail("missing/non-numeric 'ts'")
    if kind != "step":
        return
    for field, types in _STEP_REQUIRED.items():
        v = rec.get(field)
        if not isinstance(v, types) or isinstance(v, bool):
            fail(f"missing/mistyped required step field {field!r}")
        if v < 0:
            fail(f"negative {field!r}: {v}")
    for field in _STEP_NUMERIC_OPT:
        if field in rec and (not isinstance(rec[field], (int, float))
                             or isinstance(rec[field], bool)):
            fail(f"non-numeric optional field {field!r}")
    if "metrics" in rec:
        m = rec["metrics"]
        if not isinstance(m, dict):
            fail("'metrics' must be an object")
        for k, v in m.items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                fail(f"non-numeric metrics[{k!r}]")


def validate_run_log(path: str, *, require_steps: int = 0) -> int:
    """Validate every record in a JSONL run log; returns the number of
    step records. Raises ValueError on the first malformed record or if
    fewer than ``require_steps`` step records are present."""
    steps = 0
    records = read_run_log(path)
    for i, rec in enumerate(records):
        validate_record(rec, index=i)
        if rec.get("kind", "step") == "step":
            steps += 1
    if steps < require_steps:
        raise ValueError(
            f"{path}: {steps} step records < required {require_steps}")
    return steps
