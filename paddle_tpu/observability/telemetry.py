"""Hot-path step telemetry: the glue between a training/eval loop and
the registry + JSONL log + recompile detector + cross-host view.

``StepTelemetry`` is what Trainer.fit (and Executor.train_from_dataset)
actually drive: one object owning the per-step bookkeeping so the loops
stay one-call-per-step. It is deliberately tolerant — telemetry must
never take down a training run, so device-memory polling and cross-host
aggregation are individually guarded.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

from paddle_tpu.observability import aggregate as _agg
from paddle_tpu.observability import recompile as _recompile
from paddle_tpu.observability import registry as _registry
from paddle_tpu.observability import runlog as _runlog


def device_memory_stats() -> Dict[str, Dict[str, float]]:
    """Per-local-device memory stats where the backend exposes them
    (PJRT ``memory_stats``; TPU and recent CPU plugins do, some don't).
    Returns {} when unavailable — callers treat memory as optional."""
    out: Dict[str, Dict[str, float]] = {}
    try:
        import jax
        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", lambda: None)()
            if not stats:
                continue
            keep = {k: float(v) for k, v in stats.items()
                    if k in ("bytes_in_use", "peak_bytes_in_use",
                             "bytes_limit", "largest_alloc_size")}
            if keep:
                out[f"{d.platform}:{d.id}"] = keep
    except Exception:
        return {}
    return out


def record_memory_gauges(reg: Optional[_registry.MetricsRegistry] = None
                         ) -> Dict[str, Dict[str, float]]:
    """Poll device memory into ``device_memory_bytes`` gauges; returns
    the raw stats dict (for the JSONL record)."""
    reg = reg or _registry.default()
    stats = device_memory_stats()
    if stats:
        g = reg.gauge("device_memory_bytes",
                      "per-device PJRT memory stats")
        for dev, kv in stats.items():
            for stat, v in kv.items():
                g.set(v, device=dev, stat=stat)
    return stats


class StepTelemetry:
    """Per-step metrics for one training run.

    Owns: step-time/throughput histograms + counters in ``registry``,
    an optional JSONL :class:`~paddle_tpu.observability.runlog.RunLogWriter`,
    a :class:`~paddle_tpu.observability.recompile.RecompileDetector`, and
    (multi-host) periodic min/max/mean aggregation printed via ``log_fn``.

    Loop protocol::

        tel = StepTelemetry("train", run_log=path)
        for ...:
            t0 = perf(); batch = next(it); tel.data_wait(perf() - t0)
            t1 = perf(); state, m = step(state, **batch)
            tel.step(gstep, feeds=batch, step_time_s=perf() - t1,
                     examples=bsz, metrics=m, epoch=e)
        tel.close()

    Step wall time is dispatch-clocked (JAX async dispatch): in steady
    state the device back-pressures the host loop so per-step times are
    honest; the first post-compile steps can look fast.
    """

    def __init__(self, name: str = "train", *,
                 run_log: Optional[str] = None,
                 run_meta: Optional[Dict[str, Any]] = None,
                 registry: Optional[_registry.MetricsRegistry] = None,
                 log_fn: Callable[[str], None] = print,
                 memory_every: int = 50,
                 aggregate_every: int = 0,
                 detect_recompiles: bool = True):
        self.name = name
        self.reg = registry or _registry.default()
        self.log_fn = log_fn
        self.memory_every = memory_every
        self.aggregate_every = aggregate_every
        self.writer = None
        if run_log:
            self.writer = _runlog.RunLogWriter(
                run_log, meta=dict(run_meta or {}, name=name))
        self.detector = (_recompile.RecompileDetector(
            f"{name}_step", log_fn=log_fn) if detect_recompiles else None)
        self._wait_s = 0.0
        self._steps = 0
        self._h_step = self.reg.histogram(
            f"{name}_step_seconds", "per-step wall time")
        self._h_wait = self.reg.histogram(
            f"{name}_data_wait_seconds", "host blocked on the input "
            "pipeline per step")
        self._c_steps = self.reg.counter(f"{name}_steps_total")
        self._c_examples = self.reg.counter(f"{name}_examples_total")
        self._c_tokens = self.reg.counter(f"{name}_tokens_total")
        self._g_eps = self.reg.gauge(f"{name}_examples_per_sec",
                                     "throughput of the latest step")

    # -- per-step protocol -------------------------------------------------
    def data_wait(self, seconds: float):
        """Host time spent blocked fetching the next batch."""
        self._wait_s = max(0.0, float(seconds))
        self._h_wait.observe(self._wait_s)

    def step(self, step: int, *, step_time_s: float, examples: int,
             feeds: Optional[Dict[str, Any]] = None,
             tokens: Optional[int] = None,
             metrics: Optional[Dict[str, float]] = None,
             epoch: Optional[int] = None) -> Dict[str, Any]:
        """Record one completed step; returns the JSONL record (also
        written to the run log when one is attached)."""
        step_time_s = max(float(step_time_s), 1e-9)
        self._steps += 1
        self._h_step.observe(step_time_s)
        self._c_steps.inc()
        self._c_examples.inc(examples)
        eps = examples / step_time_s
        self._g_eps.set(eps)
        if self.detector is not None:
            self.detector.check(step=step, feeds=feeds)
        # the data-wait vs compute split is (data_wait_s, step_time_s):
        # fetch blocking is OUTSIDE the step timer, so step_time_s IS the
        # compute (dispatch) side — no separate compute_s field
        rec: Dict[str, Any] = {
            "kind": "step", "step": int(step),
            "step_time_s": round(step_time_s, 6),
            "examples_per_sec": round(eps, 3),
            "data_wait_s": round(self._wait_s, 6),
        }
        if tokens:
            self._c_tokens.inc(tokens)
            rec["tokens_per_sec"] = round(tokens / step_time_s, 3)
        if epoch is not None:
            rec["epoch"] = int(epoch)
        if self.detector is not None:
            rec["recompiles"] = self.detector.recompiles
            rec["compiles_cum"] = self.detector.compiles_cum
        if metrics:
            try:
                rec["metrics"] = {k: float(v) for k, v in metrics.items()}
            except Exception:
                pass  # non-scalar fetches: skip rather than sync/crash
        try:
            import jax
            if jax.process_count() > 1:
                rec["host"] = jax.process_index()
        except Exception:
            pass
        if self.memory_every and self._steps % self.memory_every == 0:
            mem = record_memory_gauges(self.reg)
            if mem:
                rec["memory"] = mem
        self._wait_s = 0.0
        if self.writer is not None:
            self.writer.write(rec)
        if (self.aggregate_every
                and self._steps % self.aggregate_every == 0):
            self.aggregate_line(rec)
        return rec

    # -- cross-host --------------------------------------------------------
    def aggregate_line(self, rec: Dict[str, Any]):
        """Multi-host: all-gather the step's headline numbers and print
        the min/mean/max skew line from host 0. Single-host: no-op."""
        try:
            import jax
            if jax.process_count() == 1:
                return
            stats = _agg.aggregate({
                "step_time_s": rec["step_time_s"],
                "examples_per_sec": rec["examples_per_sec"],
                "data_wait_s": rec.get("data_wait_s", 0.0),
            })
            if jax.process_index() == 0:
                self.log_fn(f"[observability] step {rec['step']} "
                            + _agg.format_aggregate(stats))
        except Exception as e:  # telemetry must never kill the run
            self.log_fn(f"[observability] aggregate failed: {e}")

    def close(self, summary: Optional[Dict[str, Any]] = None):
        if self.writer is not None:
            rec = {"kind": "summary", "steps": self._steps}
            s = self._h_step.summary()
            rec["step_time_mean_s"] = round(s["mean"], 6)
            rec["step_time_max_s"] = round(s["max"], 6)
            if self.detector is not None:
                rec["recompiles"] = self.detector.recompiles
                rec["compiles_cum"] = self.detector.compiles_cum
            if summary:
                rec.update(summary)
            self.writer.write(rec)
            self.writer.close()
