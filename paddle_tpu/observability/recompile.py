"""Recompilation detection: surface silent mid-training retraces.

The dominant TPU-side performance failure mode is a jitted step silently
recompiling every step (shape drift in the input pipeline, a weak-type
flip, a Python-hashable static arg changing). XLA gives no hot-path
signal — the step just takes seconds instead of milliseconds — so this
module listens to ``jax.monitoring``'s compile-duration events (emitted
once per backend compile, cache hits excluded), keeps a process-wide
count, and lets the Trainer snapshot it per step: a count increase after
warmup is a retrace, logged as a structured warning with the function
name and the offending batch's arg-shape signature.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from paddle_tpu.observability import registry as _registry

# any of these firing == one backend compile happened in-process
_COMPILE_EVENTS = ("/jax/core/compile/backend_compile_duration",)

_lock = threading.Lock()
_installed = False
_count = 0


def _on_duration(event: str, duration: float, **kw):
    global _count
    if event in _COMPILE_EVENTS:
        with _lock:
            _count += 1
        _registry.counter(
            "jax_compiles_total",
            "backend compiles observed via jax.monitoring").inc()
        _registry.histogram(
            "jax_compile_seconds",
            "backend compile wall time").observe(duration)


def install_compile_listener():
    """Idempotently hook jax.monitoring's compile-duration stream.

    Degrades gracefully: if this jax has no (or a renamed) monitoring
    API, detection stays silently off (compile_count() == 0 forever)
    rather than taking down the training loop — telemetry must never
    kill a run. One attempt per process either way."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True  # one attempt per process, success or not
    try:
        import jax.monitoring
        jax.monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception as e:
        import warnings
        warnings.warn(
            f"[observability] jax.monitoring unavailable ({e}); "
            "recompile detection disabled", RuntimeWarning)


def compile_count() -> int:
    """Backend compiles observed in this process since the listener was
    installed (0 before :func:`install_compile_listener`)."""
    with _lock:
        return _count


def shape_signature(feeds: Optional[Dict[str, Any]]) -> str:
    """Stable ``name:dtype[shape]`` signature of a feed dict — the
    retrace warning's 'what changed' half."""
    if not feeds:
        return "<no feeds>"

    def one(v):
        shape = getattr(v, "shape", None)
        dtype = getattr(v, "dtype", None)
        if shape is None:
            return f"{type(v).__name__}"
        ds = getattr(dtype, "name", str(dtype))
        return f"{ds}[{','.join(map(str, shape))}]"

    return " ".join(f"{k}:{one(v)}" for k, v in sorted(feeds.items()))


class RecompileDetector:
    """Per-callsite retrace watcher around the global compile counter.

    Protocol (what Trainer.fit does):
      det = RecompileDetector("train_step")
      ... run step ...
      new = det.check(step=i, feeds=batch)   # compiles since last check
    The first ``warmup`` checks that see compiles are expected (initial
    trace) and counted but not warned about; any later increase fires a
    structured warning via ``log_fn`` and bumps the
    ``<name>_recompiles_total`` counter.
    """

    def __init__(self, name: str = "step",
                 *, warmup: int = 1,
                 registry: Optional[_registry.MetricsRegistry] = None,
                 log_fn: Callable[[str], None] = None):
        install_compile_listener()
        self.name = name
        self.warmup = warmup
        self._reg = registry or _registry.default()
        self._log = log_fn if log_fn is not None else _warn
        self._baseline = compile_count()
        self._last = self._baseline
        self._checks = 0
        self.compiles_cum = 0     # compiles since construction
        self.recompiles = 0       # compiles after warmup (true retraces)

    def check(self, *, step: Optional[int] = None,
              feeds: Optional[Dict[str, Any]] = None) -> int:
        """Call once per step AFTER the step ran. Returns the number of
        new compiles observed since the previous check."""
        now = compile_count()
        new = now - self._last
        self._last = now
        self._checks += 1
        self.compiles_cum = now - self._baseline
        if new and self._checks > self.warmup:
            self.recompiles += new
            self._reg.counter(
                f"{self.name}_recompiles_total",
                "post-warmup retraces (shape/dtype drift)").inc(new)
            at = f" step={step}" if step is not None else ""
            self._log(
                f"[observability] RECOMPILATION: fn={self.name}{at} "
                f"new_compiles={new} total_retraces={self.recompiles} — "
                f"arg signature: {shape_signature(feeds)} (a mid-training "
                "retrace usually means input shape/dtype drift; pad or "
                "bucket the batch)")
        return new


def _warn(msg: str):
    import warnings
    warnings.warn(msg, RuntimeWarning, stacklevel=3)
